"""Serving-plane chaos drills (the ISSUE-16 robustness PR): circuit
breakers ejecting gray replicas and re-admitting them through half-open
probes, the retry budget degrading hedges instead of amplifying load,
end-to-end response-integrity nonces catching corrupted payloads,
front-door brownout with hysteresis, discovery freezing (not aging out
the fleet) under a coordinator partition, the exit-3 bootstrap marker —
and the seeded multi-fault soak that runs all five serving fault kinds
concurrently under live traffic and proves ZERO wrong payloads."""

import glob
import os
import random
import socket
import threading
import time

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402,F401

from edl_tpu.models import mlp  # noqa: E402
from edl_tpu.observability.collector import get_counters  # noqa: E402
from edl_tpu.observability.metrics import (  # noqa: E402
    get_registry,
    parse_exposition,
)
from edl_tpu.runtime.faults import (  # noqa: E402
    SERVING_KINDS,
    ChaosProxy,
    FaultContext,
    FaultPlan,
    FaultPlanEngine,
    GrayReplica,
)
from edl_tpu.runtime.frontdoor import (  # noqa: E402
    SERVING_ADDR_PREFIX,
    BatchApp,
    CoordBootstrapError,
    FrontDoor,
    bootstrap_kv,
    build_predict_request,
    format_serving_addr,
    replica_main,
)
from edl_tpu.runtime.lb import (  # noqa: E402
    BRK_CLOSED,
    BRK_OPEN,
    ServingLB,
    lb_main,
)

from tests.test_frontdoor import connect, read_responses  # noqa: E402
from tests.test_lb import PARAMS, SIZES, FakeKV, spin_replica  # noqa: E402

_REF: dict[float, np.ndarray] = {}


def ref_out(v: float) -> np.ndarray:
    """The ground-truth model output for a constant-``v`` row — what a
    response body must decode to, or it counts as a WRONG payload."""
    if v not in _REF:
        _REF[v] = np.asarray(
            mlp.apply(PARAMS, np.full((1, SIZES[0]), v, np.float32)))[0]
    return _REF[v]


def payload_ok(body: bytes, v: float) -> bool:
    out = np.frombuffer(body, "<f4")
    exp = ref_out(v)
    return out.shape == exp.shape and bool(np.allclose(out, exp, atol=1e-4))


class PartitionableKV(FakeKV):
    """FakeKV whose discovery reads can be severed for a window — the
    raising mode models the coordinator RPC timing out mid-partition,
    the empty mode models a server-side KV wipe (TTL expiry after the
    partition heals before the replicas republish)."""

    def __init__(self):
        super().__init__()
        self._until = 0.0
        self._mode = "raise"

    def partition(self, duration_s, mode="raise"):
        self._mode = mode
        self._until = time.monotonic() + duration_s

    def partitioned(self):
        return time.monotonic() < self._until

    def kv_keys(self, prefix=""):
        if self.partitioned():
            if self._mode == "raise":
                raise OSError("coordinator unreachable (injected)")
            return []
        return super().kv_keys(prefix)


def wait_routable(lb, n, deadline_s=30.0):
    end = time.monotonic() + deadline_s
    while time.monotonic() < end:
        if sum(1 for u in lb.app.upstreams.values() if u.routable()) >= n:
            return True
        time.sleep(0.05)
    return False


# ---------------------------------------------------------------------------
# Breaker lifecycle + response integrity (one two-replica fleet)
# ---------------------------------------------------------------------------


class TestBreakerAndIntegrity:
    """Gray replica ra behind a breaker-armed LB: error-mode grays trip
    the breaker (eject → half-open probe → re-admit), corrupt-mode grays
    are caught by the per-block nonce and masked by rescue resends —
    the client NEVER sees a wrong payload."""

    JOB = "chaos/fleet"

    @classmethod
    def setup_class(cls):
        import tempfile

        cls.kv = FakeKV()
        cls.app_a, cls.door_a = spin_replica(cls.kv, cls.JOB, "ra")
        cls.app_b, cls.door_b = spin_replica(cls.kv, cls.JOB, "rb")
        cls.flight = tempfile.mkdtemp(prefix="edl-chaos-flight-")
        # hedging off (floor=cap=60 s): these drills pin the breaker and
        # the nonce check, not hedge masking
        cls.lb = ServingLB(
            job=cls.JOB, host="127.0.0.1", kv=cls.kv, pool=2,
            discovery_s=0.1, sweep_ms=3.0,
            hedge_floor_ms=60000.0, hedge_cap_ms=60000.0,
            request_timeout_s=20.0,
            breaker_errors=3, breaker_ratio=0.5, breaker_min=10,
            breaker_window_s=0.5, breaker_cooldown_s=0.25,
            breaker_probes=1, flight_dir=cls.flight).start()
        assert wait_routable(cls.lb, 2), cls.lb.app.upstreams

    @classmethod
    def teardown_class(cls):
        cls.lb.stop()
        cls.door_a.stop()
        cls.door_b.stop()

    # two concurrent bursts so BOTH upstreams take load each round (the
    # least-outstanding picker would otherwise tie-break to one) — this
    # is also what routes the half-open probe to the recovering replica
    def _round(self, v=1.0, k=8):
        out = []
        s1, s2 = connect(self.lb.port), connect(self.lb.port)
        try:
            req = build_predict_request(
                np.full((SIZES[0],), v, np.float32))
            s1.sendall(req * k)
            s2.sendall(req * k)
            out.extend(read_responses(s1, k, timeout=30))
            out.extend(read_responses(s2, k, timeout=30))
        finally:
            s1.close()
            s2.close()
        return out

    def _breaker(self, name):
        up = self.lb.app.upstreams.get(name)
        return None if up is None else up.breaker.state

    def _drive_until(self, predicate, deadline_s=15.0, v=1.0):
        wrong = errs = total = 0
        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            for st, body in self._round(v=v):
                total += 1
                if st == 200:
                    if not payload_ok(body, v):
                        wrong += 1
                else:
                    errs += 1
            if predicate():
                return wrong, errs, total
            time.sleep(0.01)
        raise AssertionError(
            f"predicate never held (breaker={self._breaker('ra')}, "
            f"total={total}, errs={errs})")

    def test_error_gray_trips_breaker_then_half_open_readmit(self):
        c = get_counters()
        trans0 = {t: c.get("lb_breaker_transitions", job=self.JOB, to=t)
                  for t in ("open", "half_open", "closed")}
        self.app_a.set_gray(1.0, "error", duration_s=2.0)
        wrong, _, _ = self._drive_until(
            lambda: self._breaker("ra") == BRK_OPEN)
        assert wrong == 0
        assert c.get("lb_breaker_transitions", job=self.JOB,
                     to="open") > trans0["open"]
        # the ejection left a post-mortem on disk (PR 11 flight path)
        assert glob.glob(os.path.join(self.flight, "*lb-breaker-open*"))
        # while OPEN, traffic lands on rb only: all 200s, all correct
        for st, body in self._round():
            assert st == 200 and payload_ok(body, 1.0)
        # gray window lapses → cooldown → HALF (sweep flips it with no
        # traffic needed) → the next round's probe closes it
        time.sleep(2.0)
        wrong, errs, _ = self._drive_until(
            lambda: self._breaker("ra") == BRK_CLOSED)
        assert wrong == 0
        assert c.get("lb_breaker_transitions", job=self.JOB,
                     to="half_open") > trans0["half_open"]
        assert c.get("lb_breaker_transitions", job=self.JOB,
                     to="closed") > trans0["closed"]
        # re-admitted: both upstreams routable again
        assert wait_routable(self.lb, 2)

    def test_metrics_render_strict_with_bounded_labels(self):
        """The new series render through the strict 0.0.4 parser, and
        the breaker gauge's upstream label set is exactly the replica
        names — no per-request/per-nonce cardinality leak."""
        text = get_registry().render()
        series = parse_exposition(text)  # raises on grammar violations
        ups = set()
        for key in series:
            # scope to THIS fleet's job: the registry is process-wide
            # and other suites' LBs legitimately own their own series
            if (key.startswith("edl_lb_breaker_state{")
                    and f'job="{self.JOB}"' in key):
                for part in key[key.index("{") + 1:-1].split(","):
                    k, _, val = part.partition("=")
                    if k == "upstream":
                        ups.add(val.strip('"'))
        assert ups and ups <= {"ra", "rb"}, ups
        assert any(k.startswith("edl_lb_breaker_transitions_total")
                   for k in series)
        assert any(k.startswith("edl_lb_integrity_failures_total")
                   for k in series)
        assert any(k.startswith("edl_lb_retry_budget_exhausted_total")
                   for k in series)
        assert any(k.startswith("edl_frontdoor_brownout_seconds_total")
                   for k in series)

    def test_corrupt_gray_caught_by_nonce_zero_wrong_payloads(self):
        """mode="corrupt" answers 200s with garbage bodies and a wrong
        nonce echo — undetectable by status code.  The LB's integrity
        check must poison the connection and rescue the block to the
        healthy replica: every client response correct, zero wrong."""
        c = get_counters()
        integ0 = c.get("lb_integrity_failures", job=self.JOB)
        self.app_a.set_gray(1.0, "corrupt", duration_s=1.0)
        deadline = time.monotonic() + 1.2
        wrong = total = 0
        while time.monotonic() < deadline:
            for st, body in self._round(v=2.0):
                total += 1
                # corruption is MASKED, not surfaced: rescue resends mean
                # the client sees a correct 200, never the garbage
                assert st == 200, st
                if not payload_ok(body, 2.0):
                    wrong += 1
        assert wrong == 0 and total >= 32
        assert c.get("lb_integrity_failures", job=self.JOB) > integ0
        # let the breaker re-admit ra before the next test reuses it
        self._drive_until(lambda: self._breaker("ra") == BRK_CLOSED,
                          v=2.0)


# ---------------------------------------------------------------------------
# Retry budget
# ---------------------------------------------------------------------------


def test_retry_budget_exhaustion_degrades_hedges(tmp_path):
    """With a zero retry budget and a near-zero hedge delay, every
    would-be hedge must degrade to single-send: answers stay correct,
    the exhaustion counter moves, and a flight record lands on disk —
    no retry-storm amplification."""
    kv = FakeKV()
    app_a, door_a = spin_replica(kv, "chaos/budget", "r0")
    app_b, door_b = spin_replica(kv, "chaos/budget", "r1")
    lb = ServingLB(
        job="chaos/budget", host="127.0.0.1", kv=kv, pool=2,
        discovery_s=0.1, sweep_ms=2.0,
        hedge_floor_ms=0.1, hedge_cap_ms=0.1,
        request_timeout_s=20.0,
        retry_budget_cap=0.0, retry_ratio=0.0,
        flight_dir=str(tmp_path)).start()
    try:
        assert wait_routable(lb, 2)
        c = get_counters()
        ex0 = c.get("lb_retry_budget_exhausted", job="chaos/budget")
        k = 64
        socks = [connect(lb.port) for _ in range(4)]
        try:
            req = build_predict_request(
                np.full((SIZES[0],), 3.0, np.float32))
            for s in socks:
                s.sendall(req * k)
            for s in socks:
                for st, body in read_responses(s, k, timeout=30):
                    assert st == 200 and payload_ok(body, 3.0)
        finally:
            for s in socks:
                s.close()
        assert c.get("lb_retry_budget_exhausted", job="chaos/budget") > ex0
        assert glob.glob(str(tmp_path / "*lb-retry-budget*"))
    finally:
        lb.stop()
        door_a.stop()
        door_b.stop()


# ---------------------------------------------------------------------------
# CoordPartition: discovery freezes, serving continues, aging re-arms
# ---------------------------------------------------------------------------


def test_coord_partition_freezes_discovery_serving_continues():
    kv = PartitionableKV()
    job = "chaos/freeze"
    app_a, door_a = spin_replica(kv, job, "ra")
    app_b, door_b = spin_replica(kv, job, "rb")
    lb = ServingLB(
        job=job, host="127.0.0.1", kv=kv, pool=2,
        discovery_s=0.05, sweep_ms=3.0, addr_grace_s=0.3,
        hedge_floor_ms=30.0, request_timeout_s=20.0).start()
    c = get_counters()

    def burst(v):
        s = connect(lb.port)
        try:
            s.sendall(build_predict_request(
                np.full((SIZES[0],), v, np.float32)) * 4)
            return read_responses(s, 4, timeout=30)
        finally:
            s.close()

    try:
        assert wait_routable(lb, 2)
        # -- phase 1: the coordinator RPC raises (partition).  The LB
        # must keep BOTH last-known targets well past addr_grace_s and
        # keep serving on them.
        f0 = c.get("lb_discovery_freezes", job=job)
        kv.partition(0.7, mode="raise")
        time.sleep(0.45)  # > addr_grace_s, still inside the partition
        assert set(lb.app.upstreams) == {"ra", "rb"}
        for st, body in burst(4.0):
            assert st == 200 and payload_ok(body, 4.0)
        assert c.get("lb_discovery_freezes", job=job) > f0
        while kv.partitioned():
            time.sleep(0.05)
        # -- phase 2: the sweep "succeeds" with ZERO targets (KV wipe).
        # Mass disappearance must freeze aging, not age out the fleet.
        time.sleep(0.2)
        f1 = c.get("lb_discovery_freezes", job=job)
        kv.partition(0.6, mode="empty")
        time.sleep(0.4)
        assert set(lb.app.upstreams) == {"ra", "rb"}
        assert lb.app._disc_frozen
        for st, body in burst(5.0):
            assert st == 200 and payload_ok(body, 5.0)
        assert c.get("lb_discovery_freezes", job=job) > f1
        while kv.partitioned():
            time.sleep(0.05)
        # -- phase 3: recovery re-arms aging.  A replica that then
        # cleanly unpublishes is dropped within addr_grace_s — the
        # freeze was an episode, not a permanent aging-off switch.
        deadline = time.monotonic() + 5
        while lb.app._disc_frozen and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not lb.app._disc_frozen
        door_a.stop()
        kv.kv_del(f"{SERVING_ADDR_PREFIX}{job}/ra")
        deadline = time.monotonic() + 5
        while "ra" in lb.app.upstreams and time.monotonic() < deadline:
            time.sleep(0.05)
        assert "ra" not in lb.app.upstreams
        assert "rb" in lb.app.upstreams
        for st, body in burst(6.0):
            assert st == 200 and payload_ok(body, 6.0)
    finally:
        lb.stop()
        door_b.stop()


# ---------------------------------------------------------------------------
# Coordinator bootstrap: jittered backoff under a hard deadline, exit 3
# ---------------------------------------------------------------------------


def _silent_listener():
    """A black-holed coordinator: accepts TCP, never answers PONG — the
    failure mode a bare connect-and-hope bootstrap would hang on."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    return srv, srv.getsockname()[1]


def test_bootstrap_kv_contract():
    assert bootstrap_kv({}, disabled="discovery disabled") is None
    with pytest.raises(CoordBootstrapError):
        bootstrap_kv({"EDL_COORD_ENDPOINT": "host:notaport"},
                     disabled="discovery disabled")


def test_lb_main_exit3_on_black_holed_coordinator(capsys, tmp_path):
    srv, port = _silent_listener()
    try:
        rc = lb_main({
            "EDL_COORD_ENDPOINT": f"127.0.0.1:{port}",
            "EDL_COORD_BOOTSTRAP_DEADLINE_S": "0.6",
            "EDL_LB_JOB": "chaos/boot",
            "EDL_FLIGHTREC_DIR": str(tmp_path),
        })
    finally:
        srv.close()
    assert rc == 3
    out = capsys.readouterr().out
    assert "lb FAILED (coordinator bootstrap:" in out
    assert "unreachable for" in out
    assert glob.glob(str(tmp_path / "*lb-coord-bootstrap*"))


def test_replica_main_exit3_on_black_holed_coordinator(capsys, tmp_path):
    srv, port = _silent_listener()
    try:
        rc = replica_main({
            "EDL_COORD_ENDPOINT": f"127.0.0.1:{port}",
            "EDL_COORD_BOOTSTRAP_DEADLINE_S": "0.6",
            "EDL_FD_MODEL": "mlp:8,16,4",
            "EDL_FD_REPLICA": "rboot",
            "EDL_FLIGHTREC_DIR": str(tmp_path),
        })
    finally:
        srv.close()
    assert rc == 3
    out = capsys.readouterr().out
    assert "frontdoor FAILED replica=rboot" in out
    assert "coordinator bootstrap" in out
    assert glob.glob(str(tmp_path / "*frontdoor-coord-bootstrap*"))


# ---------------------------------------------------------------------------
# Front-door brownout + the /admin/gray drill verb
# ---------------------------------------------------------------------------


def test_brownout_enters_on_lag_breach_and_exits_with_hysteresis():
    from edl_tpu.runtime.serving import ElasticServer

    job, replica = "chaos/brown", "r0"

    def build():
        return ElasticServer(lambda p, b: mlp.apply(p, b[0]), PARAMS)

    app = BatchApp(build, SIZES[0], job=job, replica=replica,
                   max_batch=16, max_queue_ms=0.5,
                   brownout_sustain=2, brownout_min_s=0.3)
    door = FrontDoor(app, host="127.0.0.1", job=job).start()
    try:
        assert app.wait_ready(120)
        s = connect(door.port)
        req = build_predict_request(np.full((SIZES[0],), 7.0, np.float32))
        s.sendall(req * 4)
        read_responses(s, 4)
        assert not app._brownout and app.brownouts == 0
        seconds = get_registry().counter("frontdoor_brownout_seconds")
        b0 = seconds.value(job=job, replica=replica)
        # the loop-lag probe's sustained-breach relay: the NEXT batcher
        # iteration enters brownout (the probe already proved sustain)
        app.note_lag_breach()
        deadline = time.monotonic() + 10
        while not app._brownout and time.monotonic() < deadline:
            s.sendall(req)
            read_responses(s, 1)
        assert app._brownout and app.brownouts == 1
        # degraded ≠ wrong: admitted requests still answer correctly
        s.sendall(req * 4)
        for st, body in read_responses(s, 4):
            assert st == 200 and payload_ok(body, 7.0)
        # hysteresis exit: brownout_min_s elapsed AND sustain clean ticks
        deadline = time.monotonic() + 10
        while app._brownout and time.monotonic() < deadline:
            s.sendall(req)
            read_responses(s, 1)
            time.sleep(0.02)
        assert not app._brownout
        assert seconds.value(job=job, replica=replica) > b0
        s.close()
    finally:
        door.stop()


def test_admin_gray_drill_verb():
    """/admin/gray is the out-of-process injection seam the bench leg
    drives: body "<rate> <mode> <duration_s>", malformed → 400."""
    from tests.test_frontdoor import make_replica

    app, door = make_replica("chaos/admingray")
    try:
        assert app.wait_ready(120)
        s = connect(door.port)
        body = b"1.0 error 0.4"
        s.sendall(b"POST /admin/gray HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: %d\r\n\r\n" % len(body) + body)
        (st, _), = read_responses(s, 1)
        assert st == 200
        req = build_predict_request(np.full((SIZES[0],), 8.0, np.float32))
        s.sendall(req)
        (st, _), = read_responses(s, 1)
        assert st == 500
        assert get_counters().get("frontdoor_gray_responses",
                                  job="chaos/admingray", mode="error") >= 1
        time.sleep(0.45)  # the drill window lapses on its own
        s.sendall(req)
        (st, resp), = read_responses(s, 1)
        assert st == 200 and payload_ok(resp, 8.0)
        bad = b"1.0 bogus 0.4"
        s.sendall(b"POST /admin/gray HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: %d\r\n\r\n" % len(bad) + bad)
        (st, _), = read_responses(s, 1)
        assert st == 400
        s.close()
    finally:
        door.stop()


# ---------------------------------------------------------------------------
# The seeded multi-fault soak
# ---------------------------------------------------------------------------

SOAK_SEED = 1601
N_REPLICAS = 3


def _soak_plan(seed):
    plan = FaultPlan.random(seed, kinds=SERVING_KINDS, n_faults=5,
                            first_step=3, last_step=40, min_gap=5,
                            flake_duration_s=0.8)
    # the soak asserts the breaker eject→re-admit arc, so the gray's
    # rate must be high enough to trip it; the bump is deterministic
    # (same seed → same plan) so reproducibility still holds
    for a in plan.actions:
        if isinstance(a, GrayReplica):
            a.rate = max(a.rate, 0.85)
    return plan


def test_soak_plan_seeded_reproducibility():
    p1, p2 = _soak_plan(SOAK_SEED), _soak_plan(SOAK_SEED)
    assert p1.describe() == p2.describe()
    kinds = [d["kind"] for d in p1.describe()]
    assert sorted(kinds) == sorted(SERVING_KINDS)
    assert _soak_plan(SOAK_SEED + 1).describe() != p1.describe()


@pytest.mark.slow
def test_serving_chaos_soak_zero_wrong_payloads():
    """All five serving fault kinds fire concurrently (seeded schedule,
    steps = deciseconds) against a 3-replica fleet behind chaos proxies
    while Poisson-ish traffic flows.  Invariants: ZERO wrong payloads,
    bounded error rate, the breaker arc observed, every fault injected
    and recovered exactly once, and the campaign is seed-reproducible."""
    kv = PartitionableKV()
    job = "chaos/soak"
    apps, doors, proxies, pubs = {}, {}, {}, []
    pub_stop = threading.Event()
    for i in range(N_REPLICAS):
        name = f"r{i}"
        # kv=None: the replica must NOT advertise its real door — the
        # chaos proxy in front of it is the advertised address
        apps[name], doors[name] = spin_replica(None, job, name)
        proxies[name] = ChaosProxy(("127.0.0.1", doors[name].port))

    def publish(name):
        key = f"{SERVING_ADDR_PREFIX}{job}/{name}"
        addr = f"{proxies[name].host}:{proxies[name].port}"
        while not pub_stop.is_set():
            kv.kv_set(key, format_serving_addr(addr, 2.0))
            pub_stop.wait(0.3)

    for name in apps:
        t = threading.Thread(target=publish, args=(name,), daemon=True)
        t.start()
        pubs.append(t)

    lb = ServingLB(
        job=job, host="127.0.0.1", kv=kv, pool=2,
        discovery_s=0.1, sweep_ms=3.0, addr_grace_s=1.0,
        hedge_floor_ms=25.0, hedge_cap_ms=250.0,
        request_timeout_s=2.0,
        breaker_errors=4, breaker_ratio=0.5, breaker_min=10,
        breaker_window_s=0.5, breaker_cooldown_s=0.3,
        breaker_probes=1).start()

    def partition_coord(duration_s):
        kv.partition(duration_s, mode="raise")
        until = time.monotonic() + duration_s

        def recovered():
            return (time.monotonic() >= until + 0.3
                    and len(lb.app.upstreams) == N_REPLICAS)

        return recovered

    c = get_counters()
    stop = threading.Event()
    stats = {}

    def traffic(tid):
        rng = random.Random(SOAK_SEED * 100 + tid)
        v = float(tid + 1)
        req = build_predict_request(np.full((SIZES[0],), v, np.float32))
        exp = ref_out(v)
        ok = err = wrong = 0
        s = None
        while not stop.is_set():
            if s is None:
                try:
                    s = connect(lb.port)
                except OSError:
                    err += 1
                    time.sleep(0.05)
                    continue
            k = rng.randrange(1, 5)
            try:
                s.sendall(req * k)
                resps = read_responses(s, k, timeout=6.0)
            except OSError:
                try:
                    s.close()
                except OSError:
                    pass
                s = None
                err += k
                continue
            for st, body in resps:
                if st == 200:
                    out = np.frombuffer(body, "<f4")
                    if out.shape == exp.shape and np.allclose(
                            out, exp, atol=1e-4):
                        ok += 1
                    else:
                        wrong += 1
                else:
                    err += 1
            time.sleep(min(rng.expovariate(125.0), 0.05))
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
        stats[tid] = (ok, err, wrong)

    try:
        assert wait_routable(lb, N_REPLICAS)
        plan = _soak_plan(SOAK_SEED)
        ctx = FaultContext(
            replica_proxies=proxies,
            gray={n: apps[n].set_gray for n in apps},
            serving_lb=lb.app,
            partition_coord=partition_coord,
            rng=random.Random(SOAK_SEED))
        inj0 = {k: c.get("faults_injected", type=k)
                for k in SERVING_KINDS}
        rec0 = {k: c.get("recoveries_completed", type=k)
                for k in SERVING_KINDS}
        trans0 = {t: c.get("lb_breaker_transitions", job=job, to=t)
                  for t in ("open", "half_open", "closed")}
        engine = FaultPlanEngine(plan, ctx)
        threads = [threading.Thread(target=traffic, args=(tid,),
                                    daemon=True) for tid in range(3)]
        for t in threads:
            t.start()
        t0 = time.monotonic()
        hard = t0 + 60.0
        while time.monotonic() < hard:
            engine(int((time.monotonic() - t0) * 10))
            if engine.quiescent():
                break
            time.sleep(0.02)
        quiesced = engine.quiescent()
        time.sleep(0.5)  # a little post-recovery traffic on the clean fleet
        stop.set()
        for t in threads:
            t.join(timeout=20)
        assert quiesced, (engine.unfired(), engine.fired, engine.recovered)
        # exactly-once accounting: every serving kind fired once and
        # recovered once, in the engine's audit trail AND the counters
        assert sorted(k for _, k in engine.fired) == sorted(SERVING_KINDS)
        assert sorted(engine.recovered) == sorted(SERVING_KINDS)
        for k in SERVING_KINDS:
            assert c.get("faults_injected", type=k) == inj0[k] + 1, k
            assert c.get("recoveries_completed", type=k) == rec0[k] + 1, k
        # the breaker arc was observed: eject → half-open → re-admit
        assert c.get("lb_breaker_transitions", job=job,
                     to="open") > trans0["open"]
        assert c.get("lb_breaker_transitions", job=job,
                     to="half_open") > trans0["half_open"]
        assert c.get("lb_breaker_transitions", job=job,
                     to="closed") > trans0["closed"]
        for up in lb.app.upstreams.values():
            assert up.breaker.state == BRK_CLOSED
        ok = sum(v[0] for v in stats.values())
        err = sum(v[1] for v in stats.values())
        wrong = sum(v[2] for v in stats.values())
        total = ok + err + wrong
        assert wrong == 0, f"{wrong} wrong payloads out of {total}"
        assert total >= 300, total
        assert err / total <= 0.15, f"error rate {err}/{total}"
        # same seed → the same campaign, bit for bit
        assert _soak_plan(SOAK_SEED).describe() == plan.describe()
    finally:
        stop.set()
        pub_stop.set()
        lb.stop()
        for p in proxies.values():
            p.close()
        for d in doors.values():
            d.stop()
