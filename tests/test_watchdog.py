"""StallWatchdog: deadline math, breach detection, escalation, health.

All deterministic — the watchdog takes an injectable clock, so the tests
advance time by hand instead of sleeping.
"""

from __future__ import annotations

import pytest

from edl_tpu.observability.collector import get_counters
from edl_tpu.observability.tracing import get_tracer
from edl_tpu.runtime.watchdog import Stall, StallWatchdog


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_wd(clock, **kw):
    kw.setdefault("floor_s", 1.0)
    kw.setdefault("k", 4.0)
    kw.setdefault("warmup", 3)
    kw.setdefault("alpha", 0.5)
    return StallWatchdog(clock=clock, **kw)


# -- deadline model ----------------------------------------------------------


def test_floor_rules_before_any_ewma_sample():
    clock = FakeClock()
    wd = make_wd(clock, floor_s=2.5)
    assert wd.deadline_s() == 2.5  # no beats at all
    wd.beat(0)
    assert wd.deadline_s() == 2.5  # one beat: still no interval


def test_detection_arms_at_first_beat_not_after_warmup():
    """The blind-window regression: a child that makes ONE step of
    progress and then wedges must still be caught — warmup gates only
    the EWMA's settled-ness (armed()), never detection itself.  Before
    any beat, nothing fires (bootstrap/compile/restore is unwatched)."""
    clock = FakeClock()
    wd = make_wd(clock, floor_s=1.0, warmup=3)
    clock.advance(100.0)
    assert wd.check() is None  # pre-beat silence is not a stall
    wd.beat(0)
    assert not wd.armed()  # EWMA not settled...
    clock.advance(2.0)
    stall = wd.check()  # ...but the one-step-then-wedge hang IS caught
    assert stall is not None and stall.step == 0
    assert stall.deadline_s == pytest.approx(1.0)  # floor rules pre-EWMA


def test_slow_first_interval_raises_deadline_before_warmup():
    """A legitimately slow workload is protected from the first interval
    sample onward: the EWMA term raises the deadline above the floor
    even before warmup declares it settled."""
    clock = FakeClock()
    wd = make_wd(clock, floor_s=1.0, k=4.0, warmup=3, alpha=0.5)
    wd.beat(0)
    clock.advance(5.0)  # one slow (but honest) step
    wd.beat(1)
    assert not wd.armed()
    assert wd.deadline_s() == pytest.approx(20.0)  # 4 × 5.0 > floor
    clock.advance(10.0)  # silence < the raised deadline
    assert wd.check() is None


def test_floor_clamps_fast_steps():
    """Sub-millisecond steps must not produce a sub-millisecond deadline
    — the floor absorbs EWMA noise."""
    clock = FakeClock()
    wd = make_wd(clock, floor_s=1.0, k=4.0)
    for s in range(5):
        clock.advance(0.001)
        wd.beat(s)
    assert wd.ewma_s() == pytest.approx(0.001)
    assert wd.deadline_s() == 1.0  # max(floor, 4 * 0.001)


def test_deadline_grows_after_legitimately_slow_step():
    """One slow step (checkpoint barrier, recompile) raises the EWMA so
    the NEXT pause of similar size is not a false positive."""
    clock = FakeClock()
    wd = make_wd(clock, floor_s=0.1, k=4.0, alpha=0.5)
    for s in range(4):
        clock.advance(0.2)
        wd.beat(s)
    d_fast = wd.deadline_s()
    assert d_fast == pytest.approx(4.0 * 0.2)
    clock.advance(5.0)  # a legitimately slow step completes (no breach
    wd.beat(4)          # check ran during it)
    assert wd.deadline_s() > d_fast
    assert wd.ewma_s() == pytest.approx(0.5 * 5.0 + 0.5 * 0.2)


# -- breach detection + escalation -------------------------------------------


def test_breach_fires_once_counts_and_escalates():
    clock = FakeClock()
    stalls: list[Stall] = []
    wd = make_wd(clock, floor_s=1.0, on_stall=stalls.append,
                 scope="unit-test")
    before = get_counters().get("stalls_detected", scope="unit-test")
    for s in range(4):
        clock.advance(0.1)
        wd.beat(s)
    assert wd.healthy()
    clock.advance(0.5)
    assert wd.check() is None  # within deadline
    clock.advance(0.6)  # now 1.1 s of silence > 1.0 s floor deadline
    stall = wd.check()
    assert stall is not None
    assert stall.step == 3
    assert stall.silent_s == pytest.approx(1.1)
    assert stall.deadline_s == pytest.approx(1.0)
    # detection latency is bounded: the breach was seen within 2× the
    # deadline of the last beat (the acceptance bound)
    assert stall.silent_s <= 2 * stall.deadline_s
    assert stalls == [stall]
    assert not wd.healthy()
    # one stall = one escalation: repeated checks during the same
    # silence do not re-fire
    clock.advance(5.0)
    assert wd.check() is None
    assert wd.stalls_detected == 1
    assert (get_counters().get("stalls_detected", scope="unit-test")
            == before + 1)
    names = {e.name for e in get_tracer().events(category="chaos")}
    assert "stall_detected" in names
    # a beat clears the stall and re-arms
    wd.beat(4)
    assert wd.healthy()
    clock.advance(50.0)
    assert wd.check() is not None
    assert wd.stalls_detected == 2


def test_escalation_failure_does_not_kill_the_poller():
    clock = FakeClock()

    def bad_escalation(stall):
        raise RuntimeError("boom")

    wd = make_wd(clock, floor_s=0.5, on_stall=bad_escalation)
    for s in range(3):
        clock.advance(0.01)
        wd.beat(s)
    clock.advance(1.0)
    assert wd.check() is not None  # no raise
    assert not wd.healthy()


def test_healthy_wires_into_serve_health():
    """The watchdog's verdict is a liveness check: a stalled trainer
    flips its pod's /healthz to 503."""
    import json
    import urllib.request

    from edl_tpu.observability.health import serve_health

    clock = FakeClock()
    wd = make_wd(clock, floor_s=0.5)
    srv = serve_health(0, {"trainer_progress": wd.healthy},
                       host="127.0.0.1")
    try:
        port = srv.server_address[1]

        def probe():
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        for s in range(3):
            clock.advance(0.01)
            wd.beat(s)
        code, body = probe()
        assert code == 200 and body["trainer_progress"] is True
        clock.advance(2.0)
        wd.check()
        code, body = probe()
        assert code == 503 and body["trainer_progress"] is False
    finally:
        srv.shutdown()


def test_threaded_mode_detects_real_hang():
    """Wall-clock smoke for start()/stop(): beats stop arriving and the
    daemon poller catches it."""
    import threading
    import time

    caught = threading.Event()
    wd = StallWatchdog(floor_s=0.3, k=4.0, warmup=2, alpha=0.5,
                       on_stall=lambda s: caught.set(), scope="thread-test")
    wd.start(poll_s=0.05)
    try:
        for s in range(4):
            wd.beat(s)
            time.sleep(0.02)
        # now go silent: the poller must fire within ~floor + poll slack
        assert caught.wait(timeout=3.0)
        assert not wd.healthy()
    finally:
        wd.stop()


def test_constructor_validation():
    with pytest.raises(ValueError):
        StallWatchdog(floor_s=0.0)
    with pytest.raises(ValueError):
        StallWatchdog(alpha=0.0)


def test_flight_record_dumped_on_injected_stall(tmp_path):
    """The post-mortem contract: the FIRST breach of a silence drops one
    flightrec-*.json carrying the trace ring, the counters and the
    rendered metrics — and repeated checks of the same stall don't spam
    more records."""
    import json
    import os

    from edl_tpu.observability.collector import get_counters
    from edl_tpu.observability.tracing import get_tracer

    get_tracer().instant("pre_stall_marker", category="chaos", step=5)
    get_counters().inc("flight_probe")
    clock = FakeClock()
    wd = make_wd(clock, floor_s=1.0, scope="flight-test",
                 flight_dir=str(tmp_path))
    wd.beat(5)
    clock.advance(10.0)  # injected stall: silence far past the floor
    stall = wd.check()
    assert stall is not None
    recs = [f for f in os.listdir(tmp_path) if f.startswith("flightrec-")]
    assert len(recs) == 1, recs
    doc = json.loads((tmp_path / recs[0]).read_text())
    assert doc["reason"] == "stall-flight-test"
    assert doc["extra"]["step"] == 5
    assert doc["extra"]["silent_s"] >= 1.0
    assert doc["counters"].get("flight_probe", 0) >= 1
    assert "edl_flight_probe_total" in doc["metrics_text"]
    assert any(e["name"] == "pre_stall_marker"
               for e in doc["trace_events"])
    # same silence, second check: no second record (one stall = one dump)
    clock.advance(5.0)
    assert wd.check() is None
    assert len([f for f in os.listdir(tmp_path)
                if f.startswith("flightrec-")]) == 1
    # recovery then a NEW stall dumps again (the 15 s beat gap fed the
    # EWMA, so the deadline is now k×15 — advance past it)
    wd.beat(6)
    clock.advance(100.0)
    assert wd.check() is not None
    assert len([f for f in os.listdir(tmp_path)
                if f.startswith("flightrec-")]) == 2


def test_flight_record_disabled_by_default_env(tmp_path, monkeypatch):
    """No EDL_FLIGHTREC_DIR and no flight_dir → no dump (the recorder is
    opt-in for bare watchdogs; the multihost supervisor opts in with its
    ckpt dir)."""
    monkeypatch.delenv("EDL_FLIGHTREC_DIR", raising=False)
    clock = FakeClock()
    wd = make_wd(clock, floor_s=1.0)
    assert wd.flight_dir == ""
    wd.beat()
    clock.advance(5.0)
    assert wd.check() is not None  # detection itself unaffected


def test_per_test_alarm_guard_interrupts_a_hang():
    """The suite-level tripwire (tests/conftest.py): a hung test body is
    interrupted by SIGALRM with a named TestTimeout instead of eating
    the whole tier-1 budget."""
    import time

    from tests.conftest import TestTimeout, _alarm_guard

    class FakeMarker:
        args = (0.3,)

    class FakeItem:
        nodeid = "fake.py::test_wedged"

        def get_closest_marker(self, name):
            return FakeMarker() if name == "timeout_s" else None

    t0 = time.monotonic()
    with pytest.raises(TestTimeout):
        with _alarm_guard(FakeItem(), "call"):
            time.sleep(30)
    assert time.monotonic() - t0 < 5.0
    # and the timer is fully disarmed afterwards
    time.sleep(0.4)  # would re-raise if the itimer leaked
