"""FakeCluster behavior: inventory accounting (reference cluster.go:176-242),
pod counting (cluster.go:117-136), parallelism actuation, chaos hook."""

import pytest

from edl_tpu.api.types import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_TPU,
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
)
from edl_tpu.cluster.base import ConflictError, PodPhase
from edl_tpu.cluster.fake import FakeCluster


def mk_job(name="j", lo=2, hi=8, cpu="1", mem="100M", tpu="0"):
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: cpu, RESOURCE_MEMORY: mem},
                    limits={RESOURCE_CPU: cpu, RESOURCE_MEMORY: mem,
                            RESOURCE_TPU: tpu},
                ),
            ),
        ),
    )


def test_inquiry_totals_and_idle(fake_cluster):
    fake_cluster.add_node("n0", cpu_milli=4000, memory_mega=8000, tpu_chips=4)
    fake_cluster.add_node("n1", cpu_milli=4000, memory_mega=8000, tpu_chips=4)
    fake_cluster.add_system_pod("sys", "n0", cpu_request_milli=500,
                                memory_request_mega=100)
    r = fake_cluster.inquiry_resource()
    assert r.node_count == 2
    assert r.cpu_total_milli == 8000
    assert r.tpu_total == 8
    assert r.cpu_request_milli == 500
    assert r.nodes.nodes_cpu_idle_milli["n0"] == 3500
    assert r.nodes.nodes_cpu_idle_milli["n1"] == 4000
    assert r.nodes.nodes_memory_free_mega["n0"] == 7900


def test_create_resources_runs_min_instances(fake_cluster):
    fake_cluster.add_node("n0", cpu_milli=4000, memory_mega=8000)
    job = mk_job(lo=2)
    fake_cluster.create_resources(job)
    counts = fake_cluster.job_pods(job)
    assert counts.total == 2 and counts.running == 2 and counts.pending == 0
    assert fake_cluster.get_trainer_parallelism(job) == 2


def test_pods_pend_when_cluster_full(fake_cluster):
    fake_cluster.add_node("n0", cpu_milli=1000, memory_mega=8000)
    job = mk_job(lo=3, cpu="1")
    fake_cluster.create_resources(job)
    counts = fake_cluster.job_pods(job)
    assert counts.total == 3 and counts.running == 1 and counts.pending == 2


def test_scale_up_and_down(fake_cluster):
    fake_cluster.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(lo=2, hi=8)
    fake_cluster.create_resources(job)
    fake_cluster.update_trainer_parallelism(job, 5)
    assert fake_cluster.job_pods(job).running == 5
    fake_cluster.update_trainer_parallelism(job, 3)
    assert fake_cluster.job_pods(job).running == 3
    # inventory reflects the pods
    r = fake_cluster.inquiry_resource()
    assert r.cpu_request_milli == 3000


def test_conflict_injection(fake_cluster):
    fake_cluster.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job()
    fake_cluster.create_resources(job)
    fake_cluster.fail_next_updates = 1
    with pytest.raises(ConflictError):
        fake_cluster.update_trainer_parallelism(job, 4)
    fake_cluster.update_trainer_parallelism(job, 4)  # retry succeeds


def test_kill_pod_gets_replaced(fake_cluster):
    fake_cluster.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(lo=2)
    fake_cluster.create_resources(job)
    victim = fake_cluster.list_pods(job_uid="default/j", role="trainer")[0]
    fake_cluster.kill_pod(victim.name)
    counts = fake_cluster.job_pods(job)
    # Failed pod still counted in total; a fresh replacement is Running.
    assert counts.running == 2


def test_pod_event_hook(fake_cluster):
    events = []
    fake_cluster.pod_event_hook = lambda pod, what: events.append((pod.name, what))
    fake_cluster.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(lo=2)
    fake_cluster.create_resources(job)
    assert [w for _, w in events] == ["start", "start"]
    fake_cluster.delete_resources(job)
    assert [w for _, w in events].count("stop") == 2


def test_delete_resources_frees_capacity(fake_cluster):
    fake_cluster.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(lo=4)
    fake_cluster.create_resources(job)
    assert fake_cluster.inquiry_resource().cpu_request_milli == 4000
    fake_cluster.delete_resources(job)
    assert fake_cluster.inquiry_resource().cpu_request_milli == 0
    assert fake_cluster.job_pods(job).total == 0


def test_succeeded_pod_marks_work_done(fake_cluster):
    # Work-queue Job semantics: one success = job complete, no replacement,
    # and terminal pods hold no resources (cluster.go:202-210).
    fake_cluster.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(lo=1, hi=1)
    fake_cluster.create_resources(job)
    pod = fake_cluster.list_pods(job_uid="default/j")[0]
    fake_cluster.kill_pod(pod.name, PodPhase.SUCCEEDED)
    r = fake_cluster.inquiry_resource()
    assert r.cpu_request_milli == 0
    counts = fake_cluster.job_pods(job)
    assert counts.succeeded == 1 and counts.running == 0


def test_ici_domain_keeps_tpu_job_together(fake_cluster):
    # Two 4-chip nodes in different ICI domains: a 3-pod 1-chip-each job
    # must not straddle domains — the third pod pends rather than cross.
    fake_cluster.add_node("a0", cpu_milli=2000, memory_mega=8000, tpu_chips=2,
                          ici_domain="podA")
    fake_cluster.add_node("b0", cpu_milli=2000, memory_mega=8000, tpu_chips=2,
                          ici_domain="podB")
    job = mk_job(lo=3, hi=3, cpu="100m", tpu="1")
    fake_cluster.create_resources(job)
    counts = fake_cluster.job_pods(job)
    assert counts.running == 2 and counts.pending == 1
    nodes = {p.node for p in fake_cluster.list_pods(job_uid="default/j")
             if p.node is not None}
    assert len(nodes) == 1  # all placed pods share one domain


def test_non_ft_job_failure_is_not_replaced(fake_cluster):
    """Zero-failure budget enforced at the Job-controller level: once any
    trainer of a non-fault_tolerant job Failed, reconcile must never
    spawn a replacement — a replacement's frozen EDL_STATIC_PEERS would
    disagree with the survivors' peer lists (ADVICE r5 item 3)."""
    fake_cluster.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(lo=2, hi=2)
    job.spec.fault_tolerant = False
    fake_cluster.create_resources(job)
    victim = fake_cluster.list_pods(job_uid="default/j", role="trainer")[0]
    fake_cluster.kill_pod(victim.name)
    counts = fake_cluster.job_pods(job)
    assert counts.failed == 1
    assert counts.running == 1  # the survivor only — no replacement
    # and it stays that way across later reconciles
    fake_cluster.reconcile()
    assert fake_cluster.job_pods(job).running == 1
    # the FT flavor of the same scenario DOES replace (contrast pin)
    ft = mk_job(name="ft", lo=2, hi=2)
    fake_cluster.create_resources(ft)
    victim = fake_cluster.list_pods(job_uid="default/ft", role="trainer")[0]
    fake_cluster.kill_pod(victim.name)
    assert fake_cluster.job_pods(ft).running == 2
