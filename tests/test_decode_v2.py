"""Decode v2 (doc/serving.md §decode-v2): speculative multi-token
steps (lossless vs single-token greedy), block-level prefix sharing
with copy-on-write, int8 KV quantization, sharded KV pools with
per-device accounting, D2D scale-down evacuation, the adaptive
chunked-prefill budget, LB affinity eviction on session end, and a
randomized churn property sweep over the block pool."""

from __future__ import annotations

import time

import jax
import numpy as np
import pytest

from edl_tpu.models import llama
from edl_tpu.models.transformer import TINY, apply, init
from edl_tpu.observability.metrics import (
    MetricsRegistry,
    get_registry,
    parse_exposition,
)
from edl_tpu.runtime.kvcache import KVBlockPool, KVPoolExhausted
from edl_tpu.runtime.serving import DecodeFleet, TokenScheduler

PARAMS = init(jax.random.PRNGKey(0), TINY)
_REF_CACHE: dict = {}

#: a prompt whose greedy continuation is a long single-token run —
#: the self-drafting n-gram drafter's best case (and the bench's)
PERIODIC = [11, 4, 11, 4, 11, 4, 11, 4]


def ref_decode(prompt, n):
    key = (tuple(prompt), n)
    if key not in _REF_CACHE:
        toks = list(prompt)
        out = []
        for _ in range(n):
            logits = apply(PARAMS, np.asarray([toks], np.int32), TINY)
            t = int(np.asarray(logits[0, -1]).argmax())
            out.append(t)
            toks.append(t)
        _REF_CACHE[key] = out
    return _REF_CACHE[key]


def make_fleet(**kw) -> DecodeFleet:
    kw.setdefault("job", "t/decode2")
    kw.setdefault("roles", {"decode": 1})
    kw.setdefault("slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("kv_blocks", 48)
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("max_blocks_per_session", 8)
    return DecodeFleet(PARAMS, TINY, **kw)


def make_pool(num_blocks=16, block_size=8, cap=8, job="t/kv2",
              **kw) -> KVBlockPool:
    kw.setdefault("registry", MetricsRegistry())
    return KVBlockPool(TINY, num_blocks, block_size, cap, job=job, **kw)


def counter_sum(name: str, job: str, match: str = "") -> float:
    """Sum of a global-registry counter across label sets for ``job``
    (job names are unique per test, so absolutes are deltas)."""
    series = parse_exposition(get_registry().render())
    return sum(v for k, v in series.items()
               if k.startswith(name) and f'job="{job}"' in k
               and match in k)


def pool_prefill(pool: KVBlockPool, sid: int, toks: list) -> None:
    """Run a real prefill through the pool's cache for one session."""
    import jax.numpy as jnp

    pool.ensure_capacity(sid, len(toks))
    _, cache = llama.prefill(
        PARAMS, pool.cache, jnp.asarray(list(toks), "int32"),
        jnp.asarray(pool.block_table(sid)), jnp.asarray(0, "int32"),
        jnp.asarray(len(toks), "int32"), TINY)
    pool.set_cache(cache)


# -- speculative decode -------------------------------------------------------


class TestSpeculativeDecode:
    def test_lossless_vs_single_token_greedy(self):
        """THE spec-decode contract: continuations are bitwise-equal
        with speculation on and off, draftable and chaotic prompts
        alike — and both match the full-context reference."""
        ps = [PERIODIC, [5, 9, 17, 33], [200, 3, 77, 4, 11, 4],
              list(PERIODIC) + [7]]
        outs = {}
        for k in (0, 4):
            fl = make_fleet(job=f"t/spec-lossless{k}", spec_tokens=k,
                            spec_ngram=3)
            try:
                ss = [fl.submit(list(p), max_new_tokens=10) for p in ps]
                outs[k] = [s.wait(120) for s in ss]
            finally:
                fl.stop(drain=False)
        assert outs[4] == outs[0]
        assert outs[0] == [ref_decode(p, 10) for p in ps]

    def test_acceptance_counters(self):
        fl = make_fleet(job="t/spec-counters", spec_tokens=4,
                        spec_ngram=3)
        try:
            ss = [fl.submit(list(PERIODIC), max_new_tokens=12)
                  for _ in range(3)]
            for s in ss:
                s.wait(120)
            rep = fl._replicas[0]
            assert rep.spec_drafted > 0
            assert 0 < rep.spec_accepted <= rep.spec_drafted
        finally:
            fl.stop(drain=False)
        assert counter_sum("edl_decode_spec_accepted_total",
                           "t/spec-counters") > 0
        assert (counter_sum("edl_decode_spec_drafted_total",
                            "t/spec-counters")
                >= counter_sum("edl_decode_spec_accepted_total",
                               "t/spec-counters"))

    def test_eos_mid_draft_truncates_identically(self):
        """EOS landing inside an accepted draft window must cut the
        continuation exactly where single-token greedy would."""
        eos = ref_decode(PERIODIC, 1)[0]  # first continuation token
        outs = {}
        for k in (0, 4):
            fl = make_fleet(job=f"t/spec-eos{k}", spec_tokens=k,
                            spec_ngram=3, eos_id=eos)
            try:
                outs[k] = fl.submit(list(PERIODIC),
                                    max_new_tokens=8).wait(120)
            finally:
                fl.stop(drain=False)
        assert outs[4] == outs[0]
        assert len(outs[0]) < 8  # EOS actually truncated


# -- prefix sharing / CoW -----------------------------------------------------


class TestPrefixSharing:
    def test_pool_admit_with_prefix_adopts_sealed_blocks(self):
        pool = make_pool()
        toks = list(range(1, 25))  # 24 tokens = 3 full blocks of 8
        pool_prefill(pool, 1, toks)
        assert pool.register_prefix(1, toks) > 0
        blocks, covered = pool.admit_with_prefix(2, toks, 32)
        # the final prompt token is always left to prefill, so exactly
        # the first two sealed blocks (16 tokens) are adopted
        assert covered == 16
        shared = pool.session_blocks(1)[:2]
        assert pool.session_blocks(2)[:2] == shared
        assert all(pool.block_refcount(b) == 2 for b in shared)
        assert blocks == pool.session_blocks(2)

    def test_fleet_prefix_hit_skips_reprefill_and_stays_stable(self):
        job = "t/prefix-fleet"
        fl = make_fleet(job=job, kv_blocks=64,
                        max_blocks_per_session=8)
        p = list(range(7, 31))  # 24 tokens
        try:
            first = fl.submit(list(p), max_new_tokens=8).wait(120)
            again = fl.submit(list(p), max_new_tokens=8).wait(120)
        finally:
            fl.stop(drain=False)
        assert again == first == ref_decode(p, 8)
        assert counter_sum("edl_kv_prefix_hits_total", job) >= 1
        assert counter_sum("edl_kv_prefix_tokens_saved_total",
                           job) >= 8

    def test_fork_session_cow_preserves_and_diverges(self):
        pool = make_pool(job="t/kv2-cow")
        toks = list(range(3, 15))  # 12 tokens: one full + one partial
        pool_prefill(pool, 1, toks)
        src = pool.export_session(1, len(toks))
        assert pool.fork_session(1, 2) == pool.session_blocks(1)
        assert all(pool.block_refcount(b) == 2
                   for b in pool.session_blocks(1))
        # CoW guard before dst writes past the shared tail: every
        # covered shared block is replaced by a private copy
        copied = pool.make_writable(2, 8, len(toks))
        assert copied == 1
        assert (pool.session_blocks(2)[1]
                != pool.session_blocks(1)[1])
        assert pool.block_refcount(pool.session_blocks(1)[1]) == 1
        assert counter_sum("edl_kv_cow_copies_total", "t/kv2-cow") == 1
        # both sides still read the SAME prefill content
        for sid in (1, 2):
            got = pool.export_session(sid, len(toks))
            for name in ("k", "v"):
                np.testing.assert_array_equal(
                    np.asarray(got[name]), np.asarray(src[name]))


# -- int8 KV quantization -----------------------------------------------------


class TestQuantizedPool:
    def test_int8_roundtrip_bounded_error_and_smaller_pool(self):
        fp = make_pool()
        q8 = make_pool(quantize="int8")
        toks = list(range(1, 13))
        pool_prefill(fp, 1, toks)
        pool_prefill(q8, 1, toks)
        ref = fp.export_session(1, len(toks))
        got = q8.export_session(1, len(toks))
        for name in ("k", "v"):
            r = np.asarray(ref[name], np.float32)
            g = np.asarray(got[name], np.float32)
            # layer 0 sees the exact symmetric per-row int8 error:
            # |err| <= 0.5 * amax/127 per token row
            bound = (np.abs(r[0]).max(axis=(-1, -2), keepdims=True)
                     / 127.0) * 0.5 + 1e-6
            assert (np.abs(r[0] - g[0]) <= bound).all()
            # deeper layers compound (their inputs already carry the
            # quantized attention readback) — loose envelope only
            assert np.abs(r - g).max() <= 0.05 * np.abs(r).max()
        assert q8.total_bytes() < 0.5 * fp.total_bytes()

    def test_d2d_import_rejects_storage_mode_mismatch(self):
        fp = make_pool()
        q8 = make_pool(quantize="int8")
        toks = list(range(1, 10))
        pool_prefill(fp, 1, toks)
        payload = fp.export_session_device(1, len(toks))
        with pytest.raises(ValueError, match="storage modes"):
            q8.reserve_import_device(7, payload)
        assert 7 not in q8.sessions()


# -- sharded pools ------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs >=2 devices")
class TestShardedPool:
    def test_heads_sharded_fleet_matches_reference(self):
        fl = make_fleet(job="t/shard-fleet", devices_per_replica=2)
        try:
            pool = fl._replicas[0].pool
            assert len(pool.devices) == 2
            assert pool.shard_axis == "heads"  # n_kv_heads 2 % 2 == 0
            ps = [[5, 9, 17, 33], list(PERIODIC)]
            ss = [fl.submit(list(p), max_new_tokens=8) for p in ps]
            assert [s.wait(120) for s in ss] \
                == [ref_decode(p, 8) for p in ps]
        finally:
            fl.stop(drain=False)

    def test_per_device_accounting_sums_and_reserves(self):
        pool = make_pool(devices=jax.devices()[:2])
        pool.ensure_capacity(1, 20)  # 3 blocks
        per = pool.per_device_used_bytes()
        assert set(per) == {0, 1}
        assert sum(per.values()) == pool.used_bytes()
        assert pool.reserved_bytes_per_device() \
            == -(-pool.total_bytes() // 2)

    @pytest.mark.skipif(len(jax.devices()) < 3,
                        reason="needs >=3 devices")
    def test_pages_sharding_when_heads_do_not_divide(self):
        # n_kv_heads 2 % 3 != 0 but 15 blocks % 3 == 0 → pages
        pool = make_pool(num_blocks=15, devices=jax.devices()[:3])
        assert pool.shard_axis == "pages"
        pool.ensure_capacity(1, 20)
        per = pool.per_device_used_bytes()
        assert sum(per.values()) == pool.used_bytes()


# -- D2D migration ------------------------------------------------------------


class TestD2DMigration:
    def test_pool_roundtrip_bitwise_with_ici_accounting(self):
        src = make_pool()
        dst = make_pool(job="t/kv2-d2d")
        toks = list(range(1, 19))
        pool_prefill(src, 1, toks)
        ref = src.export_session(1, len(toks))
        payload = src.export_session_device(1, len(toks))
        blocks = dst.reserve_import_device(1, payload)
        dst.apply_import_device(1, blocks, payload)
        got = dst.export_session(1, len(toks))
        for name in ("k", "v"):
            np.testing.assert_array_equal(np.asarray(got[name]),
                                          np.asarray(ref[name]))
        assert counter_sum("edl_kv_migration_bytes_total",
                           "t/kv2-d2d", 'path="ici"') > 0

    def test_fleet_scale_down_migrates_d2d_zero_drops(self):
        fl = make_fleet(job="t/d2d-fleet", roles={"decode": 2},
                        kv_blocks=64)
        ps = [[9, 8, 7, 6], [1, 2, 3], list(PERIODIC), [44, 45]]
        try:
            ss = [fl.submit(list(p), max_new_tokens=48) for p in ps]
            # let every session decode past its prefill first: queued
            # (cacheless) sessions would migrate without a D2D payload
            deadline = time.time() + 60
            while (time.time() < deadline
                   and not all(s.ttft_s > 0 for s in ss)):
                time.sleep(0.01)
            assert fl.scale_to(1) == 1  # mid-decode: sessions migrate
            outs = [s.wait(240) for s in ss]
        finally:
            fl.stop(drain=False)
        assert outs == [ref_decode(p, 48) for p in ps]
        assert fl.sessions_failed == 0
        assert fl.migrations >= 1
        assert fl.migration_bytes_d2d > 0
        assert fl.migration_bytes_host == 0
        assert (fl.migration_bytes_d2d
                <= fl.migration_bytes_host_roundtrip_baseline)


# -- adaptive chunked-prefill budget ------------------------------------------


class TestAdaptiveScheduler:
    def test_cold_and_budgetless_fall_back_to_static(self):
        assert TokenScheduler(
            decode_per_prefill=3).effective_decode_per_prefill() == 3
        ts = TokenScheduler(decode_per_prefill=3, tpot_budget_ms=10.0)
        ts.note_decode(5.0)  # prefill EWMA still empty → static
        assert ts.effective_decode_per_prefill() == 3
        ts2 = TokenScheduler(decode_per_prefill=5)  # no budget at all
        ts2.note_decode(100.0)
        ts2.note_prefill(100.0)
        assert ts2.effective_decode_per_prefill() == 5

    def test_slow_decode_rations_prefill_hard(self):
        ts = TokenScheduler(decode_per_prefill=2, tpot_budget_ms=10.0)
        ts.note_decode(9.5)
        ts.note_prefill(5.0)
        # headroom 0.5ms → a 5ms chunk amortizes over 10 iterations
        assert ts.effective_decode_per_prefill() == 10
        ts.note_prefill(None)  # reset interleave count only
        for _ in range(9):
            ts.note_decode()
            assert not ts.allow_prefill(decoding=1, prefill_pending=1)
        ts.note_decode()
        assert ts.allow_prefill(decoding=1, prefill_pending=1)

    def test_fast_decode_lets_prefill_run_every_iteration(self):
        ts = TokenScheduler(decode_per_prefill=4, tpot_budget_ms=10.0)
        ts.note_decode(1.0)
        ts.note_prefill(0.5)
        assert ts.effective_decode_per_prefill() == 1

    def test_no_headroom_clamps_to_ceiling(self):
        ts = TokenScheduler(decode_per_prefill=2, tpot_budget_ms=10.0)
        ts.note_decode(12.0)
        ts.note_prefill(5.0)
        assert ts.effective_decode_per_prefill() == 64


# -- LB affinity eviction on session end --------------------------------------


class TestLBAffinityEviction:
    def _lb_with_pin(self, job):
        from edl_tpu.runtime.lb import LBApp, _Cell, _OutBlock

        lb = LBApp(job=job)

        class FakeUp:
            name = "only"

            def routable(self):
                return True

            def outstanding(self):
                return 0

        lb.upstreams = {"only": FakeUp()}
        blk = _OutBlock(None, None, 1, b"", _Cell())
        blk.session = "s1"
        lb._pick_affine(blk)
        assert "s1" in lb._affinity
        return lb, blk

    def test_session_done_header_evicts_pin(self):
        lb, blk = self._lb_with_pin("t/affev-done")
        blk.acc = [b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n"
                   b"X-EDL-Session-Done: 1\r\n\r\nok"]
        lb._maybe_evict_affinity(blk)
        assert "s1" not in lb._affinity
        assert counter_sum("edl_lb_affinity_evictions_total",
                           "t/affev-done") == 1

    def test_error_response_evicts_pin(self):
        lb, blk = self._lb_with_pin("t/affev-err")
        blk.errors = 1
        lb._maybe_evict_affinity(blk)
        assert "s1" not in lb._affinity

    def test_mid_session_response_keeps_pin(self):
        lb, blk = self._lb_with_pin("t/affev-keep")
        blk.acc = [b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok"]
        lb._maybe_evict_affinity(blk)
        assert "s1" in lb._affinity


# -- fleet reserved bytes feed the resize planner -----------------------------


class TestFleetReservedBytes:
    def test_reserved_bytes_surface_matches_pools(self):
        fl = make_fleet(job="t/reserved")
        try:
            pool = fl._replicas[0].pool
            assert fl.kv_reserved_bytes_per_device() \
                == pool.reserved_bytes_per_device() > 0
        finally:
            fl.stop(drain=False)


# -- randomized churn property sweep ------------------------------------------


class TestChurnProperty:
    def test_500_op_churn_conserves_blocks_and_refcounts(self):
        """admit / extend / prefix-share / fork / CoW / migrate / free
        for 500+ randomized ops: no leaked blocks, refcounts conserve,
        and the occupancy gauge tracks distinct per-session residency
        the whole way."""
        reg = MetricsRegistry()
        pool = make_pool(num_blocks=24, block_size=4, cap=6,
                         registry=reg, replica="r0")
        rng = np.random.default_rng(19)
        lengths: dict[int, int] = {}   # sid → token count
        prompts: dict[int, list] = {}  # sid → registered-prefix tokens
        next_sid = [1]

        def check_invariants():
            distinct = set()
            refsum = 0
            for sid in list(lengths):
                bs = pool.session_blocks(sid)
                distinct.update(bs)
                refsum += len(bs)
            assert pool.blocks_used() == len(distinct)
            assert sum(pool.block_refcount(b)
                       for b in range(pool.num_blocks)) == refsum
            assert (f'edl_serving_kv_blocks_used'
                    f'{{job="t/kv2",replica="r0"}} {len(distinct)}'
                    in reg.render())

        def op_admit():
            sid = next_sid[0]
            next_sid[0] += 1
            n = int(rng.integers(2, 13))
            try:
                pool.ensure_capacity(sid, n)
            except KVPoolExhausted:
                return
            lengths[sid] = n

        def op_extend():
            if not lengths:
                return
            sid = int(rng.choice(list(lengths)))
            n = lengths[sid] + int(rng.integers(1, 5))
            try:
                pool.ensure_capacity(sid, n)
            except KVPoolExhausted:
                return
            lengths[sid] = n

        def op_share():
            if not lengths:
                return
            src = int(rng.choice(list(lengths)))
            if src not in prompts:
                toks = [int(t) for t in
                        rng.integers(1, 255, size=lengths[src])]
                pool.register_prefix(src, toks)
                prompts[src] = toks
                return
            sid = next_sid[0]
            next_sid[0] += 1
            try:
                pool.admit_with_prefix(sid, prompts[src],
                                       len(prompts[src])
                                       + int(rng.integers(1, 5)))
            except KVPoolExhausted:
                return
            lengths[sid] = len(prompts[src])

        def op_fork():
            if not lengths:
                return
            src = int(rng.choice(list(lengths)))
            sid = next_sid[0]
            next_sid[0] += 1
            pool.fork_session(src, sid)
            lengths[sid] = lengths[src]

        def op_cow():
            if not lengths:
                return
            sid = int(rng.choice(list(lengths)))
            end = lengths[sid]
            try:
                pool.make_writable(sid, max(end - 3, 0), end)
            except KVPoolExhausted:
                return

        def op_migrate():
            if not lengths:
                return
            sid = int(rng.choice(list(lengths)))
            payload = pool.export_session_device(sid, lengths[sid])
            pool.free_session(sid)
            n = lengths.pop(sid)
            prompts.pop(sid, None)
            try:
                blocks = pool.reserve_import_device(sid, payload)
            except KVPoolExhausted:
                return
            pool.apply_import_device(sid, blocks, payload)
            lengths[sid] = n

        def op_free():
            if not lengths:
                return
            sid = int(rng.choice(list(lengths)))
            pool.free_session(sid)
            del lengths[sid]
            prompts.pop(sid, None)

        ops = [op_admit, op_admit, op_extend, op_share, op_fork,
               op_cow, op_migrate, op_free, op_free]
        for i in range(520):
            ops[int(rng.integers(len(ops)))]()
            if i % 40 == 0:
                check_invariants()
        for sid in list(lengths):
            pool.free_session(sid)
            del lengths[sid]
        check_invariants()
        assert pool.blocks_used() == 0
