"""Controller + per-job updater lifecycle
(reference pkg/controller.go + pkg/updater/trainingJobUpdater.go semantics).

All timers are shrunk so the actor loops run at test speed; phases are
polled with deadlines rather than sleeps.
"""

import time

import pytest

from edl_tpu.api.types import (
    JobPhase,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
)
from edl_tpu.api.validation import ValidationError
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.controller.controller import Controller
from edl_tpu.controller.jobparser import parse_to_manifests, pod_env
from edl_tpu.controller.updater import TrainingJobUpdater


def mk_job(name="j", lo=2, hi=4, ft=True, cpu="1", mem="100M"):
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=ft,
            trainer=TrainerSpec(
                entrypoint="python train.py", workspace="/workspace",
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: cpu, RESOURCE_MEMORY: mem},
                    limits={RESOURCE_CPU: cpu, RESOURCE_MEMORY: mem},
                ),
            ),
        ),
    )


def wait_phase(get_phase, want, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if get_phase() == want:
            return True
        time.sleep(0.01)
    return get_phase() == want


def fast_controller(cluster, **kw):
    kw.setdefault("autoscaler_loop_seconds", 0.02)
    kw.setdefault("updater_convert_seconds", 0.02)
    kw.setdefault("updater_confirm_seconds", 0.01)
    return Controller(cluster, **kw)


# -- updater actor -----------------------------------------------------------


def test_updater_reaches_running():
    c = FakeCluster()
    c.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job()
    u = TrainingJobUpdater(job, c, convert_seconds=0.02, confirm_seconds=0.01)
    assert wait_phase(lambda: u.phase, JobPhase.RUNNING)
    u.stop()


def test_updater_invalid_spec_fails_fast():
    c = FakeCluster()
    job = mk_job(lo=3, hi=2)  # max < min
    u = TrainingJobUpdater(job, c, convert_seconds=0.02, confirm_seconds=0.01)
    assert wait_phase(lambda: u.phase, JobPhase.FAILED)
    assert "max_instance" in job.status.reason


def test_updater_create_timeout_fails_and_releases():
    c = FakeCluster()  # no nodes: pods never run
    job = mk_job()
    u = TrainingJobUpdater(job, c, convert_seconds=0.02, confirm_seconds=0.01,
                           create_timeout=0.1)
    assert wait_phase(lambda: u.phase, JobPhase.FAILED)
    assert c.job_pods(job).total == 0  # resources released


def test_updater_non_ft_fails_on_any_trainer_failure():
    c = FakeCluster()
    c.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(ft=False, lo=2, hi=2)
    u = TrainingJobUpdater(job, c, convert_seconds=0.02, confirm_seconds=0.01)
    assert wait_phase(lambda: u.phase, JobPhase.RUNNING)
    victim = c.list_pods(job_uid=job.full_name, role="trainer")[0]
    # fail the pod and prevent the fake job-controller from replacing it
    # before convert() observes the failure
    with c._lock:
        from edl_tpu.cluster.base import PodPhase

        c._pods[victim.name].phase = PodPhase.FAILED
    assert wait_phase(lambda: u.phase, JobPhase.FAILED)


def test_updater_ft_survives_single_failure():
    c = FakeCluster()
    c.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(ft=True, lo=2, hi=4)
    u = TrainingJobUpdater(job, c, convert_seconds=0.02, confirm_seconds=0.01)
    assert wait_phase(lambda: u.phase, JobPhase.RUNNING)
    victim = c.list_pods(job_uid=job.full_name, role="trainer")[0]
    c.kill_pod(victim.name)  # replacement spawns via reconcile
    time.sleep(0.2)
    assert u.phase == JobPhase.RUNNING
    u.stop()


def test_updater_success_when_pod_succeeds():
    from edl_tpu.cluster.base import PodPhase

    c = FakeCluster()
    c.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(lo=1, hi=1, ft=False)
    u = TrainingJobUpdater(job, c, convert_seconds=0.02, confirm_seconds=0.01)
    assert wait_phase(lambda: u.phase, JobPhase.RUNNING)
    pod = c.list_pods(job_uid=job.full_name, role="trainer")[0]
    c.kill_pod(pod.name, PodPhase.SUCCEEDED)
    assert wait_phase(lambda: u.phase, JobPhase.SUCCEEDED)


# -- controller --------------------------------------------------------------


def test_controller_end_to_end_scales_job():
    c = FakeCluster()
    c.add_node("n0", cpu_milli=10_000, memory_mega=100_000)
    ctl = fast_controller(c, max_load_desired=1.0)
    ctl.start()
    job = mk_job(lo=2, hi=8)
    ctl.submit(job)
    assert wait_phase(lambda: ctl.phase(job), JobPhase.RUNNING)
    deadline = time.time() + 5
    while time.time() < deadline and c.get_trainer_parallelism(job) < 8:
        time.sleep(0.02)
    assert c.get_trainer_parallelism(job) == 8
    ctl.stop()


def test_controller_rejects_invalid_and_duplicate():
    c = FakeCluster()
    c.add_node("n0", cpu_milli=8000, memory_mega=8000)
    ctl = fast_controller(c)
    with pytest.raises(ValidationError):
        ctl.submit(mk_job(lo=1, hi=4, ft=False))  # elastic needs FT
    job = mk_job()
    ctl.submit(job)
    with pytest.raises(ValidationError):
        ctl.submit(mk_job())  # duplicate name
    ctl.stop()


def test_controller_delete_tears_down():
    c = FakeCluster()
    c.add_node("n0", cpu_milli=8000, memory_mega=8000)
    ctl = fast_controller(c)
    ctl.start()
    job = mk_job()
    ctl.submit(job)
    assert wait_phase(lambda: ctl.phase(job), JobPhase.RUNNING)
    ctl.delete(job)
    assert c.job_pods(job).total == 0
    assert ctl.get_updater(job) is None
    ctl.stop()


# -- jobparser ---------------------------------------------------------------


def test_manifests_order_and_shape():
    from edl_tpu.api.validation import set_defaults_and_validate

    job = set_defaults_and_validate(mk_job())
    manifests = parse_to_manifests(job)
    kinds = [(m["kind"], m["metadata"]["name"]) for m in manifests]
    # FT job: coordinator (+ its Service) first, then trainer (create
    # order, reference trainingJobUpdater.go:282-293); no pserver unless
    # requested
    assert kinds == [("ReplicaSet", "j-coordinator"),
                     ("Service", "j-coordinator"),
                     ("Job", "j-trainer")]
    trainer = manifests[-1]
    assert trainer["spec"]["parallelism"] == 2
    pod = trainer["spec"]["template"]["spec"]
    assert pod["restartPolicy"] == "Never"
    assert pod["containers"][0]["resources"]["requests"]["cpu"] == "1"
    # trainer command is the launcher's FT verb, and the env contract
    # points it at the coordinator Service
    assert pod["containers"][0]["command"][-2:] == \
        ["edl_tpu.runtime.launcher", "start_trainer"]
    env = {e["name"]: e["value"] for e in pod["containers"][0]["env"]
           if "value" in e}  # downward-API entries have valueFrom
    assert env["EDL_COORD_ENDPOINT"].startswith("j-coordinator.default.svc:")


def test_manifests_pserver_only_on_request():
    from edl_tpu.api.validation import set_defaults_and_validate

    job = mk_job(ft=False, lo=2, hi=2)
    job.spec.pserver.min_instance = 2
    job.spec.pserver.max_instance = 2
    set_defaults_and_validate(job)
    kinds = [m["metadata"]["name"] for m in parse_to_manifests(job)]
    assert kinds == ["j-pserver", "j-trainer"]  # non-FT: no coordinator


def test_pod_env_contract():
    from edl_tpu.api.validation import set_defaults_and_validate

    job = set_defaults_and_validate(mk_job())
    env = pod_env(job, "trainer")
    assert env["EDL_JOB_NAME"] == "j"
    assert env["EDL_ROLE"] == "trainer"
    assert env["EDL_FAULT_TOLERANT"] == "1"
    assert env["EDL_TRAINER_MIN"] == "2"
    assert env["EDL_TRAINER_MAX"] == "4"
    assert env["EDL_COORD_PORT"] == "7164"
    assert env["EDL_ENTRY"] == "python train.py"


def test_updater_populates_replica_statuses():
    # VERDICT r1 #9: TrainingResourceStatus existed but nothing filled it
    # (reference populates it from the updater, types.go:154-162).
    from edl_tpu.api.types import ResourceState

    c = FakeCluster()
    c.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job()
    u = TrainingJobUpdater(job, c, convert_seconds=0.02, confirm_seconds=0.01)
    assert wait_phase(lambda: u.phase, JobPhase.RUNNING)
    deadline = time.time() + 5
    while time.time() < deadline and not job.status.replica_statuses:
        time.sleep(0.01)
    by_type = {s.resource_type: s for s in job.status.replica_statuses}
    assert set(by_type) == {"MASTER", "PSERVER", "TRAINER"}
    tr = by_type["TRAINER"]
    assert tr.state == ResourceState.RUNNING
    assert len(tr.resource_states) >= job.spec.trainer.min_instance
    assert all(s == ResourceState.RUNNING for s in tr.resource_states.values())
    u.stop()


def test_cli_status_verb(capsys):
    from edl_tpu import cli

    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=8000, memory_mega=8000)
    job = mk_job(name="statusjob")
    cluster.create_resources(job)
    cluster.reconcile()
    out = cli.format_status(cluster, "default", "statusjob")
    assert "job default/statusjob" in out
    assert "TRAINER" in out and "Running" in out
    assert "statusjob-trainer" in out
    # absent job renders a clear empty message, not a crash
    assert "no pods found" in cli.format_status(cluster, "default", "nope")


def test_updater_surfaces_scaling_phase():
    # the TPU addition to the reference's phase set: a resize in flight
    # (desired parallelism != running pods) shows as SCALING, then settles
    # back to RUNNING when the pod set catches up
    c = FakeCluster()
    c.add_node("n0", cpu_milli=2500, memory_mega=16000)  # room for 2
    job = mk_job(lo=2, hi=6)
    u = TrainingJobUpdater(job, c, convert_seconds=0.02, confirm_seconds=0.01)
    assert wait_phase(lambda: u.phase, JobPhase.RUNNING)
    # the autoscaler grows the job beyond current capacity: two new pods
    # sit Pending, so the resize is visibly in flight
    c.update_trainer_parallelism(job, 4)
    assert wait_phase(lambda: u.phase, JobPhase.SCALING)
    assert "2 -> 4" in job.status.reason
    c.add_node("n1", cpu_milli=2500, memory_mega=16000)
    c.reconcile()  # capacity arrives; the kubelet places the pods
    assert wait_phase(lambda: u.phase, JobPhase.RUNNING)
    u.stop()


def test_coordinator_manifest_probes_and_health_env():
    """The advertised health port must be served and probed: the manifest
    wires EDL_HEALTH_PORT into the coord process (which serves /healthz,
    coord/native/server.cc) and points liveness/readiness at it — a
    wedged coordinator gets restarted by the kubelet (reference
    docker/paddle_k8s:27-31 served :8080 the same way)."""
    from edl_tpu.api.validation import set_defaults_and_validate
    from edl_tpu.controller.jobparser import HEALTH_PORT

    job = set_defaults_and_validate(mk_job())
    coord = parse_to_manifests(job)[0]
    container = coord["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e["value"] for e in container["env"]}
    assert env["EDL_HEALTH_PORT"] == str(HEALTH_PORT)
    for probe in ("livenessProbe", "readinessProbe"):
        http = container[probe]["httpGet"]
        assert http == {"path": "/healthz", "port": HEALTH_PORT}
    ports = {p["name"]: p["containerPort"] for p in container["ports"]}
    assert ports["health"] == HEALTH_PORT


def test_controller_deployment_manifest_probes():
    """k8s/controller.yaml wires the CLI's --health-port and probes it."""
    import pathlib

    import yaml

    doc = yaml.safe_load(
        (pathlib.Path(__file__).resolve().parent.parent /
         "k8s" / "controller.yaml").read_text())
    container = doc["spec"]["template"]["spec"]["containers"][0]
    cmd = container["command"]
    assert "--health-port" in cmd
    port = int(cmd[cmd.index("--health-port") + 1])
    assert {"containerPort": port, "name": "health"} in container["ports"]
    assert container["livenessProbe"]["httpGet"]["path"] == "/healthz"
    assert container["readinessProbe"]["httpGet"]["path"] == "/healthz"


def test_ft_trainer_env_arms_mid_world_checkpoints():
    """Deployed FT trainers get a default mid-world checkpoint cadence —
    the reference's pserver residency meant a crash never lost global
    state; without this env a deployed crash would lose everything back
    to the last membership change (generation protocol, doc/design.md)."""
    from edl_tpu.api.validation import set_defaults_and_validate

    job = set_defaults_and_validate(mk_job())
    env = pod_env(job, "trainer")
    assert int(env["EDL_MH_CKPT_EVERY"]) > 0
    # non-FT jobs and non-trainer roles are not armed
    assert "EDL_MH_CKPT_EVERY" not in pod_env(job, "coordinator")
    nonft = mk_job(ft=False, lo=2, hi=2)
    set_defaults_and_validate(nonft)
    assert "EDL_MH_CKPT_EVERY" not in pod_env(nonft, "trainer")


def test_trainer_env_passthrough_overrides_defaults():
    """spec.trainer.env is the supported per-job tuning surface: values
    land in the compiled trainer manifest AFTER the EDL_* contract, so a
    user can override defaults like EDL_MH_CKPT_EVERY (or disable with
    0) without hand-editing manifests."""
    from edl_tpu.api.validation import set_defaults_and_validate

    job = mk_job()
    job.spec.trainer.env = {"EDL_MH_CKPT_EVERY": "0", "MY_KNOB": "x"}
    set_defaults_and_validate(job)
    env = pod_env(job, "trainer")
    assert env["EDL_MH_CKPT_EVERY"] == "0"  # user value beat the default
    assert env["MY_KNOB"] == "x"
    # the contract itself is not clobbered
    assert env["EDL_JOB_NAME"] == job.name
    # round-trips through the CR shape (kubectl path)
    from edl_tpu.api.serde import job_from_dict, job_to_dict

    again = job_from_dict(job_to_dict(job))
    assert again.spec.trainer.env == job.spec.trainer.env


def test_trainer_env_overrides_every_generated_key():
    """The 'user values win' contract covers ALL generated keys —
    including the ones assigned after the defaults (coordinator endpoint,
    topology), which an earlier merge point silently clobbered."""
    from edl_tpu.api.validation import set_defaults_and_validate

    job = mk_job()
    job.spec.trainer.env = {"EDL_COORD_ENDPOINT": "my-etcd.infra.svc:2379",
                            "EDL_TPU_TOPOLOGY": "4x4"}
    set_defaults_and_validate(job)
    env = pod_env(job, "trainer")
    assert env["EDL_COORD_ENDPOINT"] == "my-etcd.infra.svc:2379"
    assert env["EDL_TPU_TOPOLOGY"] == "4x4"
