"""Port of the reference planner test suite
(reference pkg/autoscaler_internal_test.go:96-438), case by case, plus
TPU slice-shape policy extensions.

The fixtures build the same cluster snapshots and jobs; the assertions are
identical.  GPU limits map to TPU chip limits.
"""

from edl_tpu.api.types import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_TPU,
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
)
from edl_tpu.cluster.resource import ClusterResource, NodeResources
from edl_tpu.scheduler.planner import (
    PlannedJob,
    elastic,
    need_tpu,
    scale_all_jobs_dry_run,
    scale_dry_run,
    search_assignable_nodes,
    sorted_jobs,
)
from edl_tpu.scheduler.topology import POW2_POLICY, UNIT_POLICY, explicit_policy


def make_job(name, cpu_req, cpu_lim, mem_req, mem_lim, tpu_lim, lo, hi, p,
             policy=UNIT_POLICY):
    """Mirror of makeJob (reference autoscaler_internal_test.go:56-94)."""
    job = TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            trainer=TrainerSpec(
                min_instance=lo,
                max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: cpu_req, RESOURCE_MEMORY: mem_req},
                    limits={
                        RESOURCE_CPU: cpu_lim,
                        RESOURCE_MEMORY: mem_lim,
                        RESOURCE_TPU: tpu_lim,
                    },
                ),
            )
        ),
    )
    return PlannedJob(config=job, parallelism=p, shape_policy=policy)


def all_idle_nodes():
    # reference autoscaler_internal_test.go:109-112
    return NodeResources(
        nodes_cpu_idle_milli={"node0": 99999},
        nodes_memory_free_mega={"node0": 99999},
    )


def test_trainer_request_limit():
    # reference :96-101
    j = make_job("name", "1k", "1k", "100Mi", "100Mi", "10", 1, 1, 1)
    assert j.cpu_request_milli() == 1_000_000
    assert j.mem_request_mega() == 105
    assert j.tpu_chip_limit() == 10


def test_scale_dry_run_satisfied():
    # reference :103-107
    r = ClusterResource(cpu_total_milli=2000, memory_total_mega=1000)
    j = make_job("name", "1000Mi", "1000Mi", "100Mi", "100Mi", "0", 1, 2, 2)
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_scale_dry_run_more_cpu():
    # reference :114-126
    r = ClusterResource(
        cpu_limit_milli=100, cpu_request_milli=100, cpu_total_milli=3000,
        memory_request_mega=100, memory_limit_mega=100, memory_total_mega=1000,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "100Mi", "100Mi", "0", 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 1


def test_scale_dry_run_no_more_cpu():
    # reference :128-141
    r = ClusterResource(
        cpu_limit_milli=1000, cpu_request_milli=1000, cpu_total_milli=1000,
        memory_request_mega=100, memory_limit_mega=100, memory_total_mega=1000,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "100Mi", "100Mi", "0", 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_scale_dry_run_more_tpu():
    # reference :143-159 (GPU → TPU chips)
    r = ClusterResource(
        cpu_total_milli=2000,
        memory_request_mega=100, memory_limit_mega=100, memory_total_mega=1000,
        tpu_limit=0, tpu_request=0, tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "10Mi", "10Mi", "1", 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 1
    # "should not scale up if the scale down parameter is true"
    assert scale_dry_run(r, j, 0, 1.0, True) == 0


def test_scale_dry_run_no_more_tpu():
    # reference :161-177
    r = ClusterResource(
        cpu_total_milli=2000,
        memory_request_mega=100, memory_limit_mega=100, memory_total_mega=1000,
        tpu_limit=10, tpu_request=10, tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "10Mi", "10Mi", "1", 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_scale_dry_run_scale_down_more_than_expected():
    # reference :179-197 — parallelism 6 with max 3: forced down one per step
    r = ClusterResource(
        cpu_limit_milli=1000, cpu_request_milli=1000, cpu_total_milli=1000,
        memory_request_mega=1000, memory_limit_mega=1000, memory_total_mega=1000,
        tpu_limit=10, tpu_request=10, tpu_total=10,
    )
    j = make_job("name", "1", "1", "10Mi", "10Mi", "0", 1, 3, 6)
    assert scale_dry_run(r, j, 0, 1.0, True) == -1
    assert scale_dry_run(r, j, -1, 1.0, True) == -1
    assert scale_dry_run(r, j, -2, 1.0, True) == -1
    assert scale_dry_run(r, j, -3, 1.0, True) == 0


def test_scale_dry_run_scale_down_to_min():
    # reference :199-217
    r = ClusterResource(
        cpu_limit_milli=5000, cpu_request_milli=5000, cpu_total_milli=3000,
        memory_request_mega=1000, memory_limit_mega=1000, memory_total_mega=1000,
        tpu_limit=10, tpu_request=10, tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "10Mi", "10Mi", "0", 1, 3, 3)
    assert scale_dry_run(r, j, 0, 1.0, True) == -1
    assert scale_dry_run(r, j, -1, 1.0, True) == -1
    assert scale_dry_run(r, j, -2, 1.0, True) == 0


def test_scale_dry_run_scale_down_full_cluster():
    # reference :219-236
    r = ClusterResource(
        cpu_limit_milli=2000, cpu_request_milli=2000, cpu_total_milli=1000,
        memory_request_mega=1000, memory_limit_mega=1000, memory_total_mega=1000,
        tpu_limit=10, tpu_request=10, tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "10Mi", "10Mi", "0", 1, 3, 3)
    assert scale_dry_run(r, j, 0, 1.0, True) == -1
    # "should not scale down if the scale down parameter is false"
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_scale_dry_run_no_mem():
    # reference :238-254
    r = ClusterResource(
        cpu_limit_milli=1000, cpu_request_milli=1000, cpu_total_milli=1000,
        memory_request_mega=1000, memory_limit_mega=1000, memory_total_mega=1000,
        tpu_limit=10, tpu_request=10, tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "100Mi", "100Mi", "0", 1, 3, 1)
    assert scale_dry_run(r, j, 0, 1.0, False) == 0


def test_scale_all_dry_run_no_mem():
    # reference :256-269
    r = ClusterResource(
        cpu_total_milli=1000,
        memory_request_mega=1000, memory_limit_mega=1000, memory_total_mega=1000,
        tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "1", "1", "1", 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 1.0)["default/name"] == 0


def test_scale_all_dry_run():
    # reference :271-288
    r = ClusterResource(
        cpu_limit_milli=1000, cpu_request_milli=1000, cpu_total_milli=4000,
        memory_request_mega=100, memory_limit_mega=100, memory_total_mega=1000,
        tpu_limit=8, tpu_request=8, tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "100Mi", "100Mi", "0", 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 1.0)["default/name"] == 2


def test_scale_all_dry_run_not_full():
    # reference :290-307 — maxLoadDesired 0.8 leaves headroom unused
    r = ClusterResource(
        cpu_limit_milli=1000, cpu_request_milli=1000, cpu_total_milli=3000,
        memory_request_mega=100, memory_limit_mega=100, memory_total_mega=1000,
        tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "100Mi", "100Mi", "0", 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 0.8)["default/name"] == 1


def test_scale_all_dry_run_down_not_full():
    # reference :309-326 — over the 0.8 ceiling: scale down
    r = ClusterResource(
        cpu_limit_milli=3000, cpu_request_milli=3000, cpu_total_milli=3000,
        memory_request_mega=100, memory_limit_mega=100, memory_total_mega=1000,
        tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "100Mi", "100Mi", "0", 1, 3, 3)
    assert scale_all_jobs_dry_run([j], r, 0.8)["default/name"] == -1


def test_scale_all_dry_run_less_cpu():
    # reference :328-345 — CPU runs out before chips
    r = ClusterResource(
        cpu_limit_milli=2000, cpu_request_milli=2000, cpu_total_milli=3000,
        memory_request_mega=100, memory_limit_mega=100, memory_total_mega=1000,
        tpu_limit=8, tpu_request=8, tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "1", "1", "1", 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 1.0)["default/name"] == 1


def test_scale_all_dry_run_less_tpu():
    # reference :347-364 — chips run out before CPU
    r = ClusterResource(
        cpu_limit_milli=990, cpu_request_milli=990, cpu_total_milli=2000,
        memory_request_mega=100, memory_limit_mega=100, memory_total_mega=1000,
        tpu_limit=9, tpu_request=9, tpu_total=10,
        nodes=all_idle_nodes(),
    )
    j = make_job("name", "1", "1", "1", "1", "1", 1, 3, 1)
    assert scale_all_jobs_dry_run([j], r, 1.0)["default/name"] == 1


def test_fulfillment():
    # reference :366-375
    assert make_job("name", "1", "1", "1", "1", "1", 1, 2, 2).fulfillment() == 1.0
    assert make_job("name", "1", "1", "1", "1", "1", 1, 2, 1).fulfillment() == 0.0
    assert make_job("name", "1", "1", "1", "1", "1", 1, 3, 2).fulfillment() == 0.5


def test_sorted_jobs():
    # reference :377-398 — 'd' dropped by elastic filter; needy first
    jobs = [
        make_job("a", "1", "1", "1", "1", "1", 1, 2, 2),
        make_job("b", "1", "1", "1", "1", "1", 1, 20, 2),
        make_job("c", "1", "1", "1", "1", "1", 1, 10, 2),
        make_job("d", "1", "1", "1", "1", "1", 1, 1, 2),
    ]
    assert [j.name for j in sorted_jobs(jobs, elastic)] == ["b", "c", "a"]


def test_sorted_jobs_tpu_only():
    # reference :400-420 — accelerator filter
    jobs = [
        make_job("a", "1", "1", "1", "1", "1", 1, 2, 2),
        make_job("b", "1", "1", "1", "1", "0", 1, 20, 2),
        make_job("c", "1", "1", "1", "1", "0", 1, 10, 2),
        make_job("d", "1", "1", "1", "1", "0", 1, 1, 2),
    ]
    assert [j.name for j in sorted_jobs(jobs, need_tpu)] == ["a"]


def test_sorted_jobs_with_tie():
    # reference :422-438 — equal fulfillment, tiebreak chips→CPU→mem
    jobs = [
        make_job("a", "1", "0", "1", "1", "1", 1, 2, 1),
        make_job("b", "1", "1", "1", "1", "0", 1, 2, 1),
        make_job("c", "10", "10", "1", "1", "0", 1, 2, 1),
        make_job("d", "1", "1", "2", "2", "0", 1, 2, 1),
    ]
    assert [j.name for j in sorted_jobs(jobs, elastic)] == ["b", "d", "c", "a"]


# ---------------------------------------------------------------------------
# TPU slice-shape policy extensions (no reference equivalent: GPU workers
# scale ±1; TPU meshes scale between valid shapes).
# ---------------------------------------------------------------------------


def big_cluster(cpu=64_000, mem=64_000, tpu=0):
    return ClusterResource(
        cpu_total_milli=cpu, memory_total_mega=mem, tpu_total=tpu,
        nodes=NodeResources(
            nodes_cpu_idle_milli={"node0": cpu},
            nodes_memory_free_mega={"node0": mem},
        ),
    )


def test_pow2_policy_steps_through_valid_counts():
    j = make_job("j", "1", "1", "1Mi", "1Mi", "0", 1, 8, 1, policy=POW2_POLICY)
    diff = scale_all_jobs_dry_run([j], big_cluster(), 1.0)
    assert diff["default/j"] == 7  # 1 → 2 → 4 → 8, total +7


def test_pow2_policy_stops_at_largest_valid_count_below_max():
    # max 6 is not a power of two: the planner stops at 4 (the largest valid
    # count <= max) and never actuates an invalid mesh size.
    j = make_job("j", "1", "1", "1Mi", "1Mi", "0", 1, 6, 1, policy=POW2_POLICY)
    diff = scale_all_jobs_dry_run([j], big_cluster(), 1.0)
    assert 1 + diff["default/j"] == 4


def test_pow2_policy_rejects_partial_steps():
    # Room for only 1 more instance: the 2→4 step (needs 2) must not happen.
    r = ClusterResource(
        cpu_total_milli=3000, cpu_request_milli=2000,
        memory_total_mega=64_000,
        nodes=NodeResources(
            nodes_cpu_idle_milli={"node0": 1000},
            nodes_memory_free_mega={"node0": 64_000},
        ),
    )
    j = make_job("j", "1", "1", "1Mi", "1Mi", "0", 1, 8, 2, policy=POW2_POLICY)
    diff = scale_all_jobs_dry_run([j], r, 1.0)
    assert diff["default/j"] == 0


def test_pow2_policy_scale_down_steps():
    # Overloaded cluster: 8 → 4 in one policy step.
    r = ClusterResource(
        cpu_total_milli=1000, cpu_request_milli=8000, memory_total_mega=1000,
    )
    j = make_job("j", "1", "1", "1Mi", "1Mi", "0", 2, 8, 8, policy=POW2_POLICY)
    assert scale_dry_run(r, j, 0, 1.0, True) == -4
    assert scale_dry_run(r, j, -4, 1.0, True) == -2
    # at min=2: stop
    assert scale_dry_run(r, j, -6, 1.0, True) == 0


def test_explicit_policy_snaps_to_slice_worker_counts():
    pol = explicit_policy([1, 4, 8, 16])
    j = make_job("j", "1", "1", "1Mi", "1Mi", "0", 1, 16, 1, policy=pol)
    diff = scale_all_jobs_dry_run([j], big_cluster(), 1.0)
    assert 1 + diff["default/j"] == 16


def test_planner_does_not_mutate_input_snapshot():
    # The reference relies on pass-by-value (autoscaler.go:296); we copy.
    r = big_cluster()
    before = (r.cpu_request_milli, dict(r.nodes.nodes_cpu_idle_milli))
    j = make_job("j", "1", "1", "1Mi", "1Mi", "0", 1, 4, 1)
    scale_all_jobs_dry_run([j], r, 1.0)
    assert (r.cpu_request_milli, dict(r.nodes.nodes_cpu_idle_milli)) == before


def test_two_jobs_share_cluster_fairly():
    # Two identical elastic jobs on a cluster with room for 6 trainers total:
    # the fixpoint should land them at equal-ish fulfillment, both >= min.
    # snapshot already accounts the two running trainers (one per job)
    r = ClusterResource(
        cpu_total_milli=6000, cpu_request_milli=2000, memory_total_mega=64_000,
        nodes=NodeResources(
            nodes_cpu_idle_milli={"node0": 4000},
            nodes_memory_free_mega={"node0": 64_000},
        ),
    )
    a = make_job("a", "1", "1", "1Mi", "1Mi", "0", 1, 10, 1)
    b = make_job("b", "1", "1", "1Mi", "1Mi", "0", 1, 10, 1)
    diff = scale_all_jobs_dry_run([a, b], r, 1.0)
    assert diff["default/a"] + diff["default/b"] == 4  # all 6 CPUs in use
    assert abs((1 + diff["default/a"]) - (1 + diff["default/b"])) <= 1


# -- ICI-domain contiguity (TPU extension; VERDICT r1 #5) --------------------
#
# A chip job's mesh must ride ICI, so the planner may never plan instances
# of one job across ICI domains — previously only the fake kubelet enforced
# this (post-plan, stranding the overflow Pending).


def two_domain_cluster():
    """Two ICI domains of 2 nodes x 2 chips each (4 chips per domain)."""
    nodes = NodeResources(
        nodes_cpu_idle_milli={n: 8000 for n in ("a0", "a1", "b0", "b1")},
        nodes_memory_free_mega={n: 16000 for n in ("a0", "a1", "b0", "b1")},
        nodes_tpu_free={n: 2 for n in ("a0", "a1", "b0", "b1")},
        nodes_ici_domain={"a0": "A", "a1": "A", "b0": "B", "b1": "B"},
    )
    return ClusterResource(
        cpu_total_milli=32_000, memory_total_mega=64_000, tpu_total=8,
        nodes=nodes,
    )


def test_planner_caps_chip_job_at_one_ici_domain():
    # 2 chips per trainer, wants up to 4 trainers (8 chips) — but one domain
    # holds only 4 chips: the plan must stop at 2 trainers, not split 2+2
    # across domains for the kubelet to strand.
    j = make_job("j", "1", "1", "1Mi", "1Mi", "2", 0, 4, 0)
    diff = scale_all_jobs_dry_run([j], two_domain_cluster(), 1.0)
    assert diff["default/j"] == 2


def test_planner_respects_existing_domain_pin():
    # The job already runs a chip pod in domain B: growth stays in B even
    # though A has equal headroom.
    r = two_domain_cluster()
    r.jobs_ici_domain["default/j"] = "B"
    r.nodes.nodes_tpu_free["b1"] = 0  # b1 chips already in use elsewhere
    r.tpu_limit = 2
    j = make_job("j", "1", "1", "1Mi", "1Mi", "2", 0, 4, 0)
    diff = scale_all_jobs_dry_run([j], r, 1.0)
    assert diff["default/j"] == 1  # only b0's 2 chips remain in domain B


def test_planner_prefers_roomier_domain():
    # Unpinned job, domain A has 2 free chips, domain B has 4: the single
    # +1 step (2 chips) must land in B so a later step can still grow there.
    r = two_domain_cluster()
    r.nodes.nodes_tpu_free["a0"] = 0
    r.nodes.nodes_tpu_free["a1"] = 0
    r.tpu_limit = 4
    j = make_job("j", "1", "1", "1Mi", "1Mi", "2", 0, 2, 0)
    diff = scale_all_jobs_dry_run([j], r, 1.0)
    assert diff["default/j"] == 2
    assert r.jobs_ici_domain == {}  # dry-run pins only its own copy


def test_two_chip_jobs_land_in_distinct_domains():
    # Two jobs of 2x2-chip trainers: each fills one whole domain; neither
    # spans, and together they pack the cluster to 100%.
    a = make_job("a", "1", "1", "1Mi", "1Mi", "2", 0, 2, 0)
    b = make_job("b", "1", "1", "1Mi", "1Mi", "2", 0, 2, 0)
    r = two_domain_cluster()
    diff = scale_all_jobs_dry_run([a, b], r, 1.0)
    assert diff["default/a"] == 2 and diff["default/b"] == 2
    assert r.tpu_total == 8


def test_planner_and_fake_kubelet_agree_on_domains():
    # End-to-end agreement: actuating the domain-aware plan on the fake
    # cluster leaves NO pod stranded Pending on a domain boundary.
    from edl_tpu.cluster.fake import FakeCluster

    cluster = FakeCluster()
    for name, dom in (("a0", "A"), ("a1", "A"), ("b0", "B"), ("b1", "B")):
        cluster.add_node(name, cpu_milli=8000, memory_mega=16000,
                         tpu_chips=2, ici_domain=dom)
    j = make_job("j", "1", "1", "1Mi", "1Mi", "2", 1, 4, 1)
    cluster.create_resources(j.config)
    cluster.reconcile()
    r = cluster.inquiry_resource()
    assert r.jobs_ici_domain  # the running chip pod pinned its domain
    diff = scale_all_jobs_dry_run([j], r, 1.0)
    target = j.parallelism + diff["default/j"]
    assert target == 2  # one domain's 4 chips = 2 trainers
    cluster.update_trainer_parallelism(j.config, target)
    cluster.reconcile()
    counts = cluster.job_pods(j.config)
    assert counts.pending == 0 and counts.running == target


# -- multi-slice (DCN-spanning) opt-in (VERDICT r2 missing #5) ---------------
#
# trainer.allow_multi_domain lets a job whose gradient sync rides DCN span
# ICI domains; without it, elastic growth deliberately caps at the largest
# domain.


def make_multi_domain_job(name, lo, hi, p, chips="2"):
    j = make_job(name, "1", "1", "1Mi", "1Mi", chips, lo, hi, p)
    j.config.spec.trainer.allow_multi_domain = True
    return j


def test_multi_domain_job_spans_domains():
    # 2 chips/trainer, max 4 trainers = 8 chips = BOTH domains: with the
    # opt-in the plan reaches max instead of capping at one domain's 4.
    j = make_multi_domain_job("j", 0, 4, 0)
    r = two_domain_cluster()
    diff = scale_all_jobs_dry_run([j], r, 1.0)
    assert diff["default/j"] == 4
    assert r.jobs_ici_domain == {}  # spanning jobs are never pinned


def test_multi_domain_job_consolidates_when_it_fits():
    # A job that fits one domain must still land in ONE domain (most free
    # chips first), not fragment across fabrics.
    j = make_multi_domain_job("j", 0, 2, 0)
    found = search_assignable_nodes(two_domain_cluster(), j, 2)
    assert found is not None
    nodes, domain = found
    assert domain is None  # no pin for spanning jobs
    doms = {{"a0": "A", "a1": "A", "b0": "B", "b1": "B"}[n] for n in nodes}
    assert len(doms) == 1


def test_multi_domain_fake_kubelet_places_across_domains():
    # End-to-end agreement with the kubelet: an 8-chip spanning job runs
    # 4 trainers across both domains with nothing stranded Pending.
    from edl_tpu.cluster.fake import FakeCluster

    cluster = FakeCluster()
    for name, dom in (("a0", "A"), ("a1", "A"), ("b0", "B"), ("b1", "B")):
        cluster.add_node(name, cpu_milli=8000, memory_mega=16000,
                         tpu_chips=2, ici_domain=dom)
    j = make_multi_domain_job("j", 1, 4, 1)
    cluster.create_resources(j.config)
    cluster.reconcile()
    r = cluster.inquiry_resource()
    assert r.jobs_ici_domain == {}  # no pin recorded for the spanning job
    diff = scale_all_jobs_dry_run([j], r, 1.0)
    target = j.parallelism + diff["default/j"]
    assert target == 4  # both domains' 8 chips = 4 trainers
    cluster.update_trainer_parallelism(j.config, target)
    cluster.reconcile()
    counts = cluster.job_pods(j.config)
    assert counts.pending == 0 and counts.running == 4
    domains = {cluster._nodes[p.node].ici_domain
               for p in cluster.list_pods(job_uid="default/j")}
    assert domains == {"A", "B"}


def test_single_domain_default_still_caps():
    # the default stays conservative even next to a spanning job
    pinned = make_job("p", "1", "1", "1Mi", "1Mi", "2", 0, 4, 0)
    spanning = make_multi_domain_job("s", 0, 4, 0)
    r = two_domain_cluster()
    diff = scale_all_jobs_dry_run([pinned, spanning], r, 1.0)
    # the pinned job grabs one domain (4 chips = 2 trainers); the spanning
    # job takes whatever remains across fabrics
    assert diff["default/p"] == 2
    assert diff["default/s"] == 2


def test_chip_pack_to_100pct_not_reversed_by_down_pass():
    # The up-pass packs accelerators to 100% (reference NOTE,
    # autoscaler.go:270-271); the down-pass must not reverse a full pack
    # just because max_load_desired < 1 — chips drain only on true
    # over-commit.  Regression: an 8-chip cluster at mld=0.97 used to cap
    # a 2-chip-per-trainer job at 3 trainers (6 chips) forever.
    j = make_multi_domain_job("j", 0, 4, 0)
    r = two_domain_cluster()
    diff = scale_all_jobs_dry_run([j], r, 0.97)
    assert diff["default/j"] == 4  # all 8 chips packed

    # true over-commit (capacity shrank under running load) still drains
    r2 = two_domain_cluster()
    r2.tpu_total = 4  # half the chips gone; 6 committed
    r2.tpu_limit = 6
    jr = make_job("jr", "1", "1", "1Mi", "1Mi", "2", 1, 4, 3)
    assert scale_dry_run(r2, jr, 0, 0.97, True) == -1


def test_multi_domain_consolidates_via_whole_domain_try():
    # Domains: A = nodes with 4 and 2 free chips (6 total; tie on free
    # chips broken by name, so A is tried first), B = one node with 6
    # free.  Two 3-chip instances do NOT fit A (after one lands on the
    # 4-chip node, the 1+2 remainder can't take the second) but fit B
    # whole: the placement must land both in B, not spill A->B.
    nodes = NodeResources(
        nodes_cpu_idle_milli={"a0": 8000, "a1": 8000, "b0": 8000},
        nodes_memory_free_mega={"a0": 16000, "a1": 16000, "b0": 16000},
        nodes_tpu_free={"a0": 4, "a1": 2, "b0": 6},
        nodes_ici_domain={"a0": "A", "a1": "A", "b0": "B"},
    )
    r = ClusterResource(cpu_total_milli=24_000, memory_total_mega=48_000,
                        tpu_total=12, nodes=nodes)
    j = make_multi_domain_job("j", 0, 2, 0, chips="3")
    found = search_assignable_nodes(r, j, 2)
    assert found is not None
    nodes_chosen, domain = found
    assert domain is None
    assert set(nodes_chosen) == {"b0"}  # both instances in B, no DCN hop


# -- multi-domain contention stress (VERDICT r5 #8, fast half) ---------------
#
# The packing interactions planner.py:188-216 exists to get right: a
# DCN-spanning job and an ICI-pinned job fighting over the same fabrics,
# and a spanning world across domains of UNEQUAL size.  Each case asserts
# the spill order AND that actuating the plan on the fake kubelet forms
# exactly the planned world (nothing stranded Pending).


def test_spanning_and_pinned_jobs_contend_for_overlapping_domains():
    """A pinned job and a DCN-spanning job fighting over the same two
    fabrics.  Spill order: the spanning job consolidates into ONE domain
    while any domain holds its step whole, and only then spills across —
    in most-free-chips order — while the pinned job's growth never
    leaves its fabric.  Then the same contention on the fake kubelet:
    actuating the plan strands nothing Pending."""
    # controlled snapshot: P runs 2 chips on a0 (pinned to A), S runs 2
    # chips on b0; a1 and b1 each have 2 free chips
    nodes = NodeResources(
        nodes_cpu_idle_milli={n: 8000 for n in ("a0", "a1", "b0", "b1")},
        nodes_memory_free_mega={n: 16000 for n in ("a0", "a1", "b0", "b1")},
        nodes_tpu_free={"a0": 0, "a1": 2, "b0": 0, "b1": 2},
        nodes_ici_domain={"a0": "A", "a1": "A", "b0": "B", "b1": "B"},
    )
    r = ClusterResource(cpu_total_milli=32_000, memory_total_mega=64_000,
                        tpu_total=8, tpu_limit=4, nodes=nodes)
    r.jobs_ici_domain["default/p"] = "A"
    pinned = make_job("p", "1", "1", "1Mi", "1Mi", "2", 1, 2, 1)
    spanning = make_multi_domain_job("s", 1, 3, 1, chips="2")

    # spill order at the placement layer: ONE more instance consolidates
    # (fits domain A whole, the name tie-break); TWO must span — and the
    # spill walks domains most-free-first (A's a1, then B's b1)
    one, dom = search_assignable_nodes(r, spanning, 1)
    assert dom is None and [r.nodes.domain_of(n) for n in one] == ["A"]
    two, dom = search_assignable_nodes(r, spanning, 2)
    assert dom is None and two == ["a1", "b1"]  # the asserted spill order
    # the pinned job only ever sees its own fabric
    p_nodes, p_dom = search_assignable_nodes(r, pinned, 1)
    assert p_dom == "A" and all(r.nodes.domain_of(n) == "A"
                                for n in p_nodes)

    # whole-cluster fixpoint under contention: P (least fulfilled tie,
    # listed first) takes A's remainder; S's spanning growth gets B's —
    # the overlap is resolved with every chip packed and no domain split
    # for the pinned job
    diff = scale_all_jobs_dry_run([pinned, spanning], r.copy(), 1.0)
    assert pinned.parallelism + diff["default/p"] == 2
    assert spanning.parallelism + diff["default/s"] == 2

    # plan/world agreement on the kubelet: same jobs, live placement
    from edl_tpu.cluster.fake import FakeCluster

    cluster = FakeCluster()
    for name, dom_ in (("a0", "A"), ("a1", "A"), ("b0", "B"), ("b1", "B")):
        cluster.add_node(name, cpu_milli=8000, memory_mega=16000,
                         tpu_chips=2, ici_domain=dom_)
    cluster.create_resources(pinned.config)
    cluster.reconcile()  # P's first pod pins a domain
    pinned_domain = {cluster._nodes[p.node].ici_domain
                     for p in cluster.list_pods(job_uid="default/p")}
    assert len(pinned_domain) == 1
    cluster.create_resources(spanning.config)
    cluster.reconcile()
    live = cluster.inquiry_resource()
    pinned.parallelism = cluster.get_trainer_parallelism(pinned.config)
    spanning.parallelism = cluster.get_trainer_parallelism(spanning.config)
    diff = scale_all_jobs_dry_run([pinned, spanning], live, 1.0)
    targets = [(j, j.parallelism + diff[j.uid]) for j in (pinned, spanning)]
    for j, target in targets:
        cluster.update_trainer_parallelism(j.config, target)
    cluster.reconcile()
    # agreement: the world IS the plan — everything Running, nothing
    # stranded on a domain boundary, all 8 chips in use
    for j, target in targets:
        counts = cluster.job_pods(j.config)
        assert counts.pending == 0 and counts.running == target, (
            j.name, target, counts)
    assert sum(2 * t for _, t in targets) == 8
    # and the pinned job never left its fabric
    p_domains = {cluster._nodes[p.node].ici_domain
                 for p in cluster.list_pods(job_uid="default/p")}
    assert p_domains == pinned_domain


def test_spanning_world_across_unequal_domains_3_plus_1():
    """Unequal fabrics (3 + 1 free chips): a 4-chip spanning job fills
    the 3-chip domain FIRST (most-free spill order), overflows exactly
    one instance into the 1-chip domain, and the formed world matches
    the plan 3+1."""
    from edl_tpu.cluster.fake import FakeCluster

    cluster = FakeCluster()
    for name, dom, chips in (("a0", "A", 2), ("a1", "A", 1), ("b0", "B", 1)):
        cluster.add_node(name, cpu_milli=8000, memory_mega=16000,
                         tpu_chips=chips, ici_domain=dom)
    j = make_multi_domain_job("j", 1, 4, 1, chips="1")
    cluster.create_resources(j.config)
    cluster.reconcile()

    r = cluster.inquiry_resource()
    assert r.jobs_ici_domain == {}  # spanning job: no pin even when running
    # spill order at the placement layer: remaining 3 instances take A's
    # remaining 2 chips before touching B (A has the most free chips)
    nodes, dom = search_assignable_nodes(r, j, 3)
    assert dom is None
    doms = [r.nodes.domain_of(n) for n in nodes]
    assert doms[:2] == ["A", "A"] and doms[2] == "B"

    diff = scale_all_jobs_dry_run([j], r, 1.0)
    target = j.parallelism + diff["default/j"]
    assert target == 4  # both fabrics packed despite unequal shapes

    cluster.update_trainer_parallelism(j.config, target)
    cluster.reconcile()
    counts = cluster.job_pods(j.config)
    assert counts.pending == 0 and counts.running == 4
    placed = [cluster._nodes[p.node].ici_domain
              for p in cluster.list_pods(job_uid="default/j")]
    assert sorted(placed) == ["A", "A", "A", "B"]  # the planned 3+1 world
