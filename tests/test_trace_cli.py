"""Cross-process trace stitching + the `edl-tpu trace` verb
(ISSUE-14 tentpole): load_trace_events over chrome dumps AND flight
records, span-forest nesting, tree rendering, the TraceFileSink a live
data-plane process dumps through, and the CLI surface end-to-end."""

import io
import json
import os
import time
from contextlib import redirect_stdout

from edl_tpu.observability.tracing import (
    TraceFileSink,
    Tracer,
    build_span_forest,
    discover_trace_files,
    load_trace_events,
    new_trace_id,
    render_trace_tree,
)


def _two_process_trace(tmp_path, tid):
    """Simulate the LB + one replica recording one hedged request, each
    into its own tracer, dumped as separate processes' files."""
    lb, fd = Tracer(), Tracer()
    root = lb.record_span("lb_request", "lb", 0.000, 0.050,
                          trace_id=tid, n=4, origin="head",
                          outcome="served", hedged=True)
    lb.record_span("lb.route", "lb", 0.000, 0.001, trace_id=tid,
                   parent_id=root)
    lb.record_span("lb.upstream", "lb", 0.001, 0.048, trace_id=tid,
                   parent_id=root, replica="r0", kind="primary",
                   outcome="discarded")
    lb.record_span("lb.upstream", "lb", 0.020, 0.024, trace_id=tid,
                   parent_id=root, replica="r1", kind="hedge",
                   outcome="win")
    door = fd.record_span("frontdoor_request", "frontdoor", 0.021,
                          0.024, trace_id=tid, parent_id=root,
                          replica="r1", rows=4)
    fd.record_span("frontdoor.forward", "frontdoor", 0.022, 0.0235,
                   trace_id=tid, parent_id=door)
    lb.dump(str(tmp_path / "trace-lb-1.json"), "lb-1")
    fd.dump(str(tmp_path / "trace-fd-r1.json"), "fd-r1")
    return lb, fd


def test_load_and_render_stitched_cross_process_tree(tmp_path):
    tid = new_trace_id()
    _two_process_trace(tmp_path, tid)
    # noise in the same files: another trace id must not leak in
    files = discover_trace_files(str(tmp_path))
    assert len(files) == 2
    events = load_trace_events(files, tid)
    assert len(events) == 6
    assert {e["proc"] for e in events} == {"lb-1", "fd-r1"}
    roots = build_span_forest(events)
    assert len(roots) == 1 and roots[0]["name"] == "lb_request"
    # door root nests under the LB root even though it came from
    # another process's dump (parent_id stitching)
    kids = [c["name"] for c in roots[0]["children"]]
    assert kids == ["lb.route", "lb.upstream", "lb.upstream",
                    "frontdoor_request"]
    txt = render_trace_tree(events, tid)
    assert "2 processes" in txt
    assert "outcome=discarded" in txt and "outcome=win" in txt
    assert "frontdoor.forward" in txt
    assert "[fd-r1]" in txt and "[lb-1]" in txt


def test_orphan_parent_surfaces_as_root(tmp_path):
    """A span whose parent dump is missing (ring rotated, file lost)
    must surface as a root, not vanish from the tree."""
    tid = new_trace_id()
    t = Tracer()
    t.record_span("frontdoor_request", "frontdoor", 0.0, 0.01,
                  trace_id=tid, parent_id="missing-span-id")
    t.dump(str(tmp_path / "trace-orphan.json"), "fd")
    events = load_trace_events([str(tmp_path / "trace-orphan.json")],
                               tid)
    roots = build_span_forest(events)
    assert [r["name"] for r in roots] == ["frontdoor_request"]
    assert "frontdoor_request" in render_trace_tree(events, tid)


def test_flight_record_is_a_trace_source(tmp_path):
    """flightrec-*.json embeds the trace ring with a wall anchor — a
    crash's flight record is enough to recover its sampled traces."""
    from edl_tpu.observability.metrics import dump_flight_record

    tid = new_trace_id()
    t = Tracer()
    t.record_span("lb_request", "lb", 0.0, 0.02, trace_id=tid,
                  origin="rescue", outcome="served")
    path = dump_flight_record(str(tmp_path), "lb-abnormal-exit",
                              tracer=t)
    assert os.path.basename(path).startswith("flightrec-")
    events = load_trace_events([path], tid)
    assert len(events) == 1 and events[0]["name"] == "lb_request"
    assert events[0]["args"]["origin"] == "rescue"
    # discovery picks flight records up next to trace dumps
    assert path in discover_trace_files(str(tmp_path))


def test_cli_trace_verb_renders_and_errors(tmp_path, capsys):
    from edl_tpu import cli

    tid = new_trace_id()
    _two_process_trace(tmp_path, tid)
    rc = cli.main(["trace", tid, "--trace-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"trace {tid}" in out
    assert "lb_request" in out and "frontdoor_request" in out
    assert "outcome=discarded" in out
    # unknown id: exit 1 with a pointer, not a stack trace
    rc = cli.main(["trace", "no-such-trace", "--trace-dir",
                   str(tmp_path)])
    assert rc == 1
    # no sources at all: exit 2
    empty = tmp_path / "empty"
    empty.mkdir()
    rc = cli.main(["trace", tid, "--trace-dir", str(empty)])
    assert rc == 2


def test_cli_trace_explicit_files(tmp_path):
    from edl_tpu import cli

    tid = new_trace_id()
    _two_process_trace(tmp_path, tid)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["trace", tid, "--files",
                       str(tmp_path / "trace-lb-1.json")])
    assert rc == 0
    txt = buf.getvalue()
    # only the LB's half: door spans live in the other (unpassed) file
    assert "lb_request" in txt and "frontdoor_request" not in txt


def test_trace_file_sink_periodic_and_final_dump(tmp_path):
    t = Tracer()
    tid = new_trace_id()
    t.record_span("lb_request", "lb", 0.0, 0.01, trace_id=tid)
    sink = TraceFileSink(str(tmp_path), "lb-test", interval_s=0.05,
                         tracer=t)
    sink.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and sink.dumps < 2:
        time.sleep(0.02)
    assert sink.dumps >= 2
    # a late event makes it into the FINAL dump on stop()
    t.record_span("lb.upstream", "lb", 0.01, 0.02, trace_id=tid)
    sink.stop()
    with open(tmp_path / "trace-lb-test.json") as f:
        doc = json.load(f)
    names = {e["name"] for e in doc["traceEvents"]
             if e.get("ph") != "M"}
    assert {"lb_request", "lb.upstream"} <= names
    assert doc["edl"]["process"] == "lb-test"
    # no torn temp files left behind
    assert not [f for f in os.listdir(tmp_path) if ".tmp-" in f]


def test_anchorless_file_merges_degraded_not_fatal(tmp_path):
    """A foreign chrome trace without the edl wall anchor merges at raw
    timestamps instead of being dropped or shifting everything."""
    tid = new_trace_id()
    foreign = {"traceEvents": [{
        "name": "ext_span", "cat": "x", "ph": "X", "ts": 1000.0,
        "dur": 500.0, "pid": 0, "tid": 0,
        "args": {"trace_id": tid, "span_id": "e1"}}]}
    p = tmp_path / "trace-foreign.json"
    p.write_text(json.dumps(foreign))
    events = load_trace_events([str(p)], tid)
    assert len(events) == 1
    assert events[0]["ts_s"] == 0.001 and events[0]["dur_s"] == 0.0005


def test_duplicate_sources_dedupe_by_span_id(tmp_path):
    """The same ring dumped twice — a trace-*.json AND a flight record
    (EDL_TRACE_DIR == EDL_FLIGHTREC_DIR is a legitimate setup) — must
    not duplicate subtrees in the rendered tree."""
    from edl_tpu.observability.metrics import dump_flight_record

    tid = new_trace_id()
    t = Tracer()
    root = t.record_span("lb_request", "lb", 0.0, 0.05, trace_id=tid)
    t.record_span("lb.upstream", "lb", 0.001, 0.049, trace_id=tid,
                  parent_id=root, kind="primary", outcome="win")
    t.instant("lb_shed_marker", category="lb")
    t.dump(str(tmp_path / "trace-lb.json"), "lb-1")
    dump_flight_record(str(tmp_path), "loop-lag-lb", tracer=t)
    events = load_trace_events(discover_trace_files(str(tmp_path)), tid)
    assert len(events) == 2  # not 4
    roots = build_span_forest(events)
    assert len(roots) == 1
    assert [c["name"] for c in roots[0]["children"]] == ["lb.upstream"]
    assert "2 spans" in render_trace_tree(events, tid)
