"""A DCN-spanning job forms ONE world across two ICI domains.

Round-4 verdict missing #2: ``allow_multi_domain`` was planner-only — the
planner placed spanning jobs but no test ever formed a world across two
domains through placement → launcher → workers.  Here the whole chain
runs: a FakeCluster with two 2-chip ICI domains, a 4-trainer job that
CANNOT fit in either domain alone, the controller materializes it, the
process-backed kubelet execs the shipped pod commands, and the four
supervised workers — two "in" each domain — form a single world and
drain the queue exactly once.  (On real hardware the in-domain gradient
sync rides ICI and the cross-domain sync rides DCN — multi-slice data
parallelism; on CPU processes the transport is loopback, but the
placement, membership, and world-formation logic is identical.
Reference parity: its runtime executed its transport claims,
docker/paddle_k8s:14-32.)"""

from __future__ import annotations

import glob
import os
import re
import time

import pytest

from edl_tpu.cluster.exec_kubelet import ProcessKubelet
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.controller.controller import Controller

from tests.test_exec_kubelet_e2e import e2e_cr, free_port

pytestmark = [pytest.mark.slow, pytest.mark.timeout_s(840),
              # the spanning world is four REAL worker processes: on a
              # backend that can't form multi-process CPU worlds the
              # world count stays [] forever (same gate as
              # test_multihost.py; the probe's reason rides the skip)
              pytest.mark.needs_multiprocess_collectives]


def test_multidomain_job_forms_one_world(tmp_path):
    from edl_tpu.api.serde import job_from_dict

    fake = FakeCluster()
    # two ICI domains, 2 chips each: a 4-chip single-domain mesh is
    # impossible — only a DCN-spanning placement can run this job
    fake.add_node("slice-a-host", cpu_milli=16000, memory_mega=16000,
                  tpu_chips=2, ici_domain="slice-a")
    fake.add_node("slice-b-host", cpu_milli=16000, memory_mega=16000,
                  tpu_chips=2, ici_domain="slice-b")

    controller = Controller(fake, updater_convert_seconds=0.3,
                            updater_confirm_seconds=0.2)
    work = str(tmp_path)
    kubelet = ProcessKubelet(fake, work, env_overrides={
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PALLAS_AXON_POOL_IPS": "",
        "EDL_MH_DIE_WITH_PARENT": "1",
        "EDL_MH_EXAMPLES": str(16 * 1024),
        "EDL_MH_SHARDS": "32",
        "EDL_MH_BATCH": "32",
        "EDL_MH_STEP_SLEEP": "0.01",
        "EDL_HEALTH_PORT": "0",
        "EDL_COORD_MEMBER_TTL_MS": "3000",
        "EDL_MH_WARM_SPAWN": "0",
    })

    port = free_port()
    manifest = e2e_cr("span", port, os.path.join(work, "ckpt"),
                      lo=4, hi=4)
    manifest["spec"]["trainer"]["allow_multi_domain"] = True
    job = job_from_dict(manifest)

    try:
        controller.submit(job)

        # placement: the scheduler spread the 4 chip pods across BOTH
        # domains (2+2) — a non-spanning job would sit Pending forever
        deadline = time.monotonic() + 60
        placed = []
        while time.monotonic() < deadline:
            placed = [p for p in fake.list_pods(job_uid="default/span",
                                                role="trainer")
                      if p.node is not None]
            if len(placed) == 4:
                break
            time.sleep(0.2)
        assert len(placed) == 4, fake.list_pods(job_uid="default/span")
        by_node = {n: sum(1 for p in placed if p.node == n)
                   for n in ("slice-a-host", "slice-b-host")}
        assert by_node == {"slice-a-host": 2, "slice-b-host": 2}, by_node

        # the four workers — across the domain boundary — form ONE world
        # and drain the queue together
        def worlds():
            out = []
            for path in glob.glob(os.path.join(work, "logs",
                                               "span-trainer-*.log")):
                out += [int(m.group(1)) for m in re.finditer(
                    r"entering world epoch=\d+ world=(\d+)",
                    open(path).read())]
            return out

        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if any(w == 4 for w in worlds()):
                break
            time.sleep(0.5)
        assert any(w == 4 for w in worlds()), worlds()

        # drain to completion: workers exit 0 (which requires exactly-once
        # accounting — done==shards, no drops — or they exit nonzero) and
        # the job's phase machine records Succeeded
        from edl_tpu.api.types import JobPhase

        updater = controller.get_updater(job)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if updater.job.status.phase in (JobPhase.SUCCEEDED,
                                            JobPhase.FAILED):
                break
            time.sleep(0.5)
        assert updater.job.status.phase == JobPhase.SUCCEEDED, (
            updater.job.status)
        done_lines = [
            path for path in glob.glob(os.path.join(
                work, "logs", "span-trainer-*.log"))
            if "done at step" in open(path).read()
        ]
        assert done_lines, "no worker recorded a clean drain"
    finally:
        controller.stop()
        kubelet.stop()
