"""Async checkpoint pipeline: the step loop stops paying for persistence.

PR 3's checkpoint tentpole: ElasticCheckpointer.save_async snapshots
device→host at the step boundary and persists + finalizes (integrity
manifest included) on a background thread with bounded backpressure.
These tests pin: manifests exist for async saves (the save(wait=False)
gap — an async save used to be invisible to latest_verified_step
forever), the crash window between persist and finalize degrades to the
pre-manifest semantics instead of corrupting, backpressure bounds the
pipeline at one in-flight persist, and error/ENOSPC semantics survive
the move off-thread.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

import numpy as np
import pytest

from edl_tpu.runtime.checkpoint import ElasticCheckpointer


def tree(step: int):
    return {"w": np.arange(64, dtype=np.float32) * (step + 1),
            "b": np.ones((8,), np.float32) * step,
            "step": np.asarray(step, np.int32)[None]}


def test_save_async_writes_manifest_and_verifies(tmp_path):
    ck = ElasticCheckpointer(tmp_path)
    pause = ck.save_async(1, tree(1))
    assert pause >= 0.0
    ck.finalize()
    assert ck.latest_verified_step() == 1
    # the manifest is the real integrity artifact, not a vacuous pass
    mpath = Path(tmp_path) / ".integrity" / "1.json"
    assert mpath.exists()
    manifest = json.loads(mpath.read_text())
    assert manifest["files"], "async save finalized an empty manifest"
    restored = ck.restore(tree(0))
    assert float(restored["w"][1]) == 2.0
    ck.close()


def test_wait_false_manifest_written_at_finalize(tmp_path):
    """The named satellite: save(wait=False) must write its manifest at
    finalize time, not skip it forever."""
    ck = ElasticCheckpointer(tmp_path)
    ck.save(3, tree(3), wait=False)
    ck.finalize()
    assert (Path(tmp_path) / ".integrity" / "3.json").exists()
    assert ck.latest_verified_step() == 3
    ck.close()


def test_crash_between_persist_and_finalize(tmp_path):
    """Regression for the crash window: the process dies after the Orbax
    files land but before the manifest is written.  A new checkpointer
    must still restore — the step is unverifiable (pre-manifest
    semantics), not poisoned — and an older verified step still anchors
    latest_verified_step."""
    ck = ElasticCheckpointer(tmp_path)
    ck.save(1, tree(1), wait=True)  # fully finalized anchor
    ck.save(2, tree(2), wait=False)
    # simulate the crash: Orbax finishes its async write, the manifest
    # write never happens (no finalize), the process is gone
    ck._mgr.wait_until_finished()
    assert not (Path(tmp_path) / ".integrity" / "2.json").exists()
    del ck

    fresh = ElasticCheckpointer(tmp_path)
    # the un-finalized step has no manifest → it verifies VACUOUSLY (the
    # documented pre-manifest semantics: absence of a manifest is no
    # evidence against the data) and restore reads it fine — the files
    # are whole, only the fingerprint is missing
    assert fresh.latest_verified_step() == 2
    restored = fresh.restore(tree(0))
    assert int(restored["step"][0]) == 2
    fresh.close()

    # the harsher half of the window: the crash also TORE the step's
    # files.  With no manifest to catch it, Orbax's parse fails and the
    # restore must fall back to the older, finalized step — never raise
    step2 = Path(tmp_path) / "2"
    victims = [p for p in step2.rglob("*") if p.is_file()
               and p.stat().st_size > 0]
    assert victims
    for p in victims:
        p.write_bytes(p.read_bytes()[: max(p.stat().st_size // 2, 1)])
    again = ElasticCheckpointer(tmp_path)
    restored = again.restore(tree(0))
    assert int(restored["step"][0]) == 1  # fell back past the torn step
    again.close()


def test_backpressure_bounds_pipeline_to_one(tmp_path):
    """Never more than one persist in flight: the second save_async
    blocks until the first lands (its pause absorbs the wait), instead of
    queueing snapshots without bound."""
    ck = ElasticCheckpointer(tmp_path)
    big = {"w": np.zeros((512, 512), np.float32)}
    p1 = ck.save_async(1, big)
    t0 = time.monotonic()
    p2 = ck.save_async(2, big)  # must drain save 1 first
    assert ck._inflight is not None or True  # pipeline live for save 2
    ck.finalize()
    # after finalize, nothing is in flight and both steps verified
    assert ck._inflight is None
    assert sorted(s for s in (1, 2) if ck.verify(s)) == [1, 2]
    assert ck.latest_verified_step() == 2
    # pauses were recorded for percentile reporting
    assert ck.async_pauses_s == [p1, p2]
    del t0
    ck.close()


def test_async_pause_is_fraction_of_sync_save(tmp_path):
    """The acceptance shape: with the pipeline idle, an async save's
    step-loop pause is a small fraction of a synchronous save."""
    ck = ElasticCheckpointer(tmp_path)
    big = {"w": np.zeros((256, 1024), np.float32),
           "v": np.zeros((256, 1024), np.float32)}
    t0 = time.monotonic()
    ck.save(1, big, wait=True)
    sync_s = time.monotonic() - t0
    time.sleep(0.05)
    pause = ck.save_async(2, big)
    ck.finalize()  # land it before comparing
    assert pause < max(sync_s * 0.5, 0.05), (pause, sync_s)
    ck.close()


def test_skip_if_busy_drops_tick_instead_of_blocking(tmp_path):
    """The cadence policy: a tick that finds the previous persist still
    in flight is dropped (counted), never blocked on — and the next tick
    persists a newer step."""
    from edl_tpu.observability.collector import get_counters

    ck = ElasticCheckpointer(tmp_path)
    # hold the pipeline busy deterministically: a persist that waits on
    # an event the test controls
    import threading

    release = threading.Event()
    real_persist = ck._persist

    def slow_persist(step, tree, wait, best_effort):
        release.wait(timeout=10)
        return real_persist(step, tree, wait=wait, best_effort=best_effort)

    ck._persist = slow_persist
    before = get_counters().get("checkpoint_async_skipped")
    ck.save_async(1, tree(1))
    t0 = time.monotonic()
    pause = ck.save_async(2, tree(2), skip_if_busy=True)  # busy → dropped
    assert time.monotonic() - t0 < 0.5, "skip_if_busy blocked"
    assert pause < 0.5
    assert get_counters().get("checkpoint_async_skipped") == before + 1
    release.set()
    ck._persist = real_persist
    ck.wait_pending()
    assert ck.save_async(3, tree(3), skip_if_busy=True) is not None  # idle → saves
    ck.finalize()
    assert ck.latest_verified_step() == 3
    assert 2 not in ck._mgr.all_steps()  # the dropped tick never landed
    ck.close()


def test_async_error_surfaces_at_next_sync_point(tmp_path):
    ck = ElasticCheckpointer(tmp_path)
    ck.inject_save_failures(1)
    ck.save_async(1, tree(1), best_effort=False)
    with pytest.raises(OSError):
        ck.wait_pending()
    # the pipeline recovered: the next save works and finalizes
    assert ck.save(2, tree(2), wait=True)
    assert ck.latest_verified_step() == 2
    ck.close()


def test_async_best_effort_enospc_counts_and_recovers(tmp_path):
    from edl_tpu.observability.collector import get_counters

    ck = ElasticCheckpointer(tmp_path)
    before = get_counters().get("checkpoint_save_failures")
    ck.inject_save_failures(1)
    ck.save_async(1, tree(1), best_effort=True)
    ck.wait_pending()  # best-effort: no raise
    assert get_counters().get("checkpoint_save_failures") == before + 1
    rec_before = get_counters().get("recoveries_completed",
                                    type="disk_full")
    ck.save_async(2, tree(2), best_effort=True)
    ck.finalize()
    assert get_counters().get("recoveries_completed",
                              type="disk_full") == rec_before + 1
    assert ck.latest_verified_step() == 2
    ck.close()


def test_close_finalizes_pending_async_saves(tmp_path):
    ck = ElasticCheckpointer(tmp_path)
    ck.save_async(5, tree(5))
    ck.close()  # must land + finalize, not abandon the daemon thread
    fresh = ElasticCheckpointer(tmp_path)
    assert fresh.latest_verified_step() == 5
    fresh.close()


def test_saves_never_overlap(tmp_path):
    """A sync save right after an async one drains the pipeline first —
    Orbax never sees two concurrent saves of different steps."""
    ck = ElasticCheckpointer(tmp_path)
    ck.save_async(1, tree(1))
    assert ck.save(2, tree(2), wait=True)
    assert ck._inflight is None
    assert ck.latest_verified_step() == 2
    assert ck.verify(1)
    ck.close()


def test_restore_drains_inflight_persist(tmp_path):
    ck = ElasticCheckpointer(tmp_path)
    ck.save_async(1, tree(1))
    restored = ck.restore(tree(0))  # must not read under the write
    assert int(restored["step"][0]) == 1
    ck.close()


def test_later_sync_save_races_persist_thread_ordering_pinned(tmp_path):
    """PR 17 satellite: save_async(N)'s persist thread vs a concurrent
    SYNC save(N+1) from the step loop.  The sync save must queue behind
    the in-flight persist (never interleave Orbax writes), step N's
    manifest + meta sidecar must land BEFORE step N+1's, and both steps
    end fully verified with N+1 as the newest verified step."""
    import threading

    entered = threading.Event()
    release = threading.Event()

    class SlowPersist(ElasticCheckpointer):
        def _persist(self, step, tree_, wait, best_effort, meta=None):
            if step == 5:
                entered.set()
                assert release.wait(10), "test deadlock"
            return super()._persist(step, tree_, wait=wait,
                                    best_effort=best_effort, meta=meta)

    ck = SlowPersist(tmp_path)
    ck.save_async(5, tree(5), meta={"cursor": "c5"})
    assert entered.wait(10)

    done = []
    racer = threading.Thread(
        target=lambda: done.append(
            ck.save(6, tree(6), wait=True, meta={"cursor": "c6"})))
    racer.start()
    time.sleep(0.2)
    # the sync save is parked in wait_pending: NOTHING of step 6 exists
    # yet, and step 5's manifest is still owed by the stalled persist
    assert done == []
    assert not (Path(tmp_path) / ".integrity" / "6.json").exists()
    assert not (Path(tmp_path) / ".integrity" / "5.json").exists()

    release.set()
    racer.join(30)
    assert done == [True]
    # ordering landed: 5 then 6, each with its own meta sidecar
    for step, cursor in ((5, "c5"), (6, "c6")):
        manifest = json.loads(
            (Path(tmp_path) / ".integrity" / f"{step}.json").read_text())
        assert manifest["verified"] is True and manifest["tree_hash"]
        assert ck.load_meta(step)["cursor"] == cursor
    assert ck.latest_verified_step() == 6
    # finalize() after the fact owes nothing and clobbers nothing
    ck.finalize()
    assert ck.manifest_verified(5) is True
    assert ck.manifest_verified(6) is True
    restored = ck.restore(tree(0), step=6)
    assert int(restored["step"][0]) == 6
    assert ck.last_restore_hash_ok is True
    ck.close()
