"""Llama-3-8B-class FSDP evidence (BASELINE.json config 4).

No 8B-capable hardware exists here, so the evidence is two-sided
(round-3 verdict missing #2: LLAMA3_8B must not stay a dead constant):

1. the *plan*: eval_shape params + Adam state, apply the model's real
   partition specs over simulated v5p-16/32/64 meshes, assert every
   large leaf is sharded and the per-device state fits 95 GB HBM;
2. the *execution*: one real jitted training step at the 8B layer shapes
   (d_model 4096, d_ff 14336, full vocab; layer count scaled to 1) over
   a virtual 8-device fsdp mesh, with the per-device shard bytes matching
   what the plan predicted.
"""

from __future__ import annotations

import dataclasses
from math import prod

import pytest

from edl_tpu.models.planning import (
    V5P_HBM_GB,
    V5P_SLICES,
    fsdp_memory_plan,
    format_plan_table,
)
from edl_tpu.models.transformer import LLAMA3_8B
from edl_tpu.parallel.compat import set_mesh

BIG_LEAF_BYTES = 32 << 20  # anything larger must not be replicated


def test_llama8b_is_8b_class():
    plan = fsdp_memory_plan(LLAMA3_8B, 8)
    assert 7.0e9 < plan.n_params < 8.5e9, plan.n_params
    # fp32 params + 2 Adam moments = 12 bytes/param
    total_state_gb = plan.state_gb_per_device * 8
    assert total_state_gb == pytest.approx(12 * plan.n_params / 1e9,
                                           rel=0.01)


@pytest.mark.parametrize("slice_name,n_devices", sorted(V5P_SLICES.items()))
def test_plan_shards_every_big_leaf_and_fits_hbm(slice_name, n_devices):
    plan = fsdp_memory_plan(LLAMA3_8B, n_devices)
    big_replicated = [l for l in plan.leaves
                     if l.shard_factor == 1 and l.bytes_total > BIG_LEAF_BYTES]
    assert big_replicated == [], big_replicated
    # the only replicated leaves are the tiny RMSNorm scales
    for leaf in plan.replicated_leaves():
        assert leaf.bytes_total <= 32 << 10, leaf
    assert plan.fits and plan.state_gb_per_device < V5P_HBM_GB / 4, (
        slice_name, plan.state_gb_per_device)
    # growing the slice shrinks per-device state proportionally (the
    # autoscaler's v5p-16→64 growth story: more room for batch/activations)
    if n_devices > 8:
        base = fsdp_memory_plan(LLAMA3_8B, 8)
        assert plan.state_gb_per_device == pytest.approx(
            base.state_gb_per_device * 8 / n_devices, rel=0.05)


def test_plan_2d_mesh_tp_times_fsdp():
    """The 2-D variant (tp=8 within a host's ICI, fsdp across): same
    per-device state, different axis layout — both legal under the specs."""
    p1 = fsdp_memory_plan(LLAMA3_8B, 32, tp=1)
    p2 = fsdp_memory_plan(LLAMA3_8B, 32, tp=8)
    assert p2.fsdp == 4 and p2.tp == 8
    assert p2.state_gb_per_device == pytest.approx(
        p1.state_gb_per_device, rel=0.05)


def test_plan_table_matches_baseline_md():
    """BASELINE.md's config-4 table is generated from this module — keep
    the recorded numbers honest by re-deriving them here."""
    import pathlib

    table = format_plan_table(
        LLAMA3_8B, [(n, d, 1) for n, d in V5P_SLICES.items()])
    baseline = (pathlib.Path(__file__).resolve().parent.parent /
                "BASELINE.md").read_text()
    for line in table.splitlines()[2:]:
        assert line in baseline, f"BASELINE.md missing/stale row: {line}"


@pytest.mark.slow
def test_one_step_at_8b_layer_shapes_on_8dev_mesh():
    """Execute (not just plan) one training step at the real 8B layer
    shapes — d_model 4096, d_ff 14336, vocab 32000, GQA 32/8 — with the
    layer count scaled to 1 so a 1-core CI host can run it.  The mesh is
    the canonical dp×fsdp×tp×sp with fsdp=8; assertions check the
    actually-materialized shard sizes against the plan's arithmetic."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from edl_tpu.models import transformer as T

    cfg = dataclasses.replace(LLAMA3_8B, n_layers=1, max_seq_len=64,
                              use_flash=False, remat=False)
    devs = np.array(jax.devices()[:8]).reshape(1, 8, 1, 1)
    mesh = Mesh(devs, ("dp", "fsdp", "tp", "sp"))
    specs = T.param_partition_specs(cfg)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P))

    with set_mesh(mesh):
        params = jax.jit(
            lambda: T.init(jax.random.key(0), cfg),
            out_shardings=shardings)()
    opt = optax.adam(1e-4)
    opt_state = jax.jit(opt.init)(params)

    # every big param leaf is physically 8-way sharded; device 0 holds
    # 1/8th of the bytes the plan predicted
    wq = params["layers"][0]["wq"]
    assert len(wq.sharding.device_set) == 8
    assert wq.addressable_shards[0].data.shape == (4096 // 8, 4096)
    plan = fsdp_memory_plan(cfg, 8)
    dev0_bytes = sum(
        leaf.addressable_shards[0].data.nbytes
        for leaf in jax.tree.leaves(params))
    assert dev0_bytes == plan.param_bytes_per_device

    batch_sh = NamedSharding(mesh, T.batch_partition_spec())
    tokens = jax.device_put(
        np.random.default_rng(0).integers(
            0, cfg.vocab_size, (8, 64), dtype=np.int32), batch_sh)
    targets = jax.device_put(
        np.random.default_rng(1).integers(
            0, cfg.vocab_size, (8, 64), dtype=np.int32), batch_sh)

    @jax.jit
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(T.loss_fn)(
            params, (tokens, targets), cfg=cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    with set_mesh(mesh):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    loss = float(loss)
    # next-token CE on random tokens starts near ln(vocab)
    assert np.isfinite(loss) and abs(loss - np.log(cfg.vocab_size)) < 1.0
    # the update preserved the sharding (no silent gather to one device)
    wq2 = params["layers"][0]["wq"]
    assert wq2.addressable_shards[0].data.shape == (4096 // 8, 4096)
