"""Multi-process tests for the elastic multi-host runtime.

These run REAL worker processes (``python -m edl_tpu.runtime.multihost_worker``,
one single-device CPU jax process each) against a real native coordination
server, and exercise the behaviors the reference could only validate
operationally (SURVEY §4: deploy on minikube and kill pods by hand):

* a join wave forms ONE world and the task queue drains exactly-once;
* graceful scale-down: SIGTERM a worker → it leaves at a step boundary,
  survivors finish (reference trainer-count elasticity,
  docker/paddle_k8s:119-141);
* crash: ``kill -9`` a worker → the survivors' supervisors reform a smaller
  world and finish — a dead trainer is a non-event, the reference's
  headline property (master re-dispatches its leased tasks after the
  timeout, docker/paddle_k8s:30);
* a late joiner inherits trained state through the generation protocol
  instead of cold-starting.

Every scenario asserts exactly-once task accounting from the queue stats.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

from edl_tpu.coord.server import spawn_server

# every test here budgets its own subprocess waits (up to ~600 s on a
# loaded box) — the conftest SIGALRM ceiling must sit ABOVE them, or the
# per-test tripwire turns legitimate slow runs into flakes
# every scenario here forms a >=2-process jax.distributed world — gated
# on the conftest capability probe so an environment whose CPU backend
# lacks multiprocess collectives skips with a reason instead of failing
pytestmark = [pytest.mark.multihost, pytest.mark.timeout_s(840),
              pytest.mark.needs_multiprocess_collectives]

#: Enough data that scenarios are still mid-job when we inject faults
#: (shards × rows ÷ batch = 512 global steps).
EXAMPLES, SHARDS, BATCH = 16384, 64, 32
SMALL_EXAMPLES, SMALL_SHARDS = 2048, 16


def _worker_env(examples: int, shards: int) -> dict:
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        EDL_MH_EXAMPLES=str(examples),
        EDL_MH_SHARDS=str(shards),
        EDL_MH_BATCH=str(BATCH),
        # suite hygiene: killing pytest (even -9) reaps every worker tree
        EDL_MH_DIE_WITH_PARENT="1",
        # CPU workers: disarm the axon sitecustomize (≈5 s of jax import
        # per interpreter start, paid by every supervisor AND world child)
        PALLAS_AXON_POOL_IPS="",
    )
    return env


def _spawn_worker(port: int, name: str, ckpt_dir, min_members: int,
                  env: dict, log_path, *, extra=()) -> subprocess.Popen:
    log = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.runtime.multihost_worker",
         "--coord", f"127.0.0.1:{port}", "--name", name,
         "--ckpt-dir", str(ckpt_dir), "--min-members", str(min_members),
         "--settle-s", "0.3", "--heartbeat-timeout-s", "5", *extra],
        stdout=log, stderr=subprocess.STDOUT, env=env)


def _wait_all(procs: dict, timeout_s: float) -> dict:
    """Wait for every worker; returns {name: returncode}."""
    deadline = time.monotonic() + timeout_s
    rcs = {}
    for name, p in procs.items():
        rcs[name] = p.wait(timeout=max(deadline - time.monotonic(), 1.0))
    return rcs


def _wait_for_line(path, needle: str, timeout_s: float) -> str:
    """Poll a worker log until a line containing ``needle`` appears."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if path.exists():
            for line in path.read_text().splitlines():
                if needle in line:
                    return line
        time.sleep(0.25)
    raise TimeoutError(f"{needle!r} never appeared in {path}")


@pytest.fixture
def coord_server():
    handle = spawn_server(member_ttl_ms=3000, task_timeout_ms=4000)
    yield handle
    handle.stop()


def _assert_exactly_once(client, shards: int) -> None:
    """Every shard completed exactly once, none dropped — across any
    number of crashes/resizes (the queue re-dispatches a dead worker's
    leases; COMPLETE on a re-leased task counts once)."""
    s = client.stats()
    assert s.todo == 0 and s.leased == 0, s
    assert s.done == shards, s
    assert s.dropped == 0, s


@pytest.mark.slow
@pytest.mark.parametrize("data_mode", ["memory", "files"])
def test_join_wave_forms_one_world_and_drains(coord_server, tmp_path,
                                              data_mode):
    env = _worker_env(SMALL_EXAMPLES, SMALL_SHARDS)
    env["EDL_MH_TRACE"] = str(tmp_path / "traces")
    if data_mode == "files":
        # REAL shard files on shared storage (the reference's RecordIO
        # chunks): the seeder writes them once, every worker streams on
        # lease — nothing dataset-sized in worker memory up front
        env["EDL_MH_DATA_DIR"] = str(tmp_path / "shards")
    procs = {
        n: _spawn_worker(coord_server.port, n, tmp_path, 2, env,
                         tmp_path / f"{n}.log")
        for n in ("w0", "w1")
    }
    rcs = _wait_all(procs, timeout_s=180)
    assert rcs == {"w0": 0, "w1": 0}
    for n in procs:
        text = (tmp_path / f"{n}.log").read_text()
        assert "done at step" in text
        # the settle window merged the join wave into one 2-world
        assert "world=2" in text and "world=1" not in text
    _assert_exactly_once(coord_server.client(), SMALL_SHARDS)
    # the supervisor dumped a chrome trace of its world timeline
    import json as _json

    trace = _json.loads((tmp_path / "traces" / "trace-w0.json").read_text())
    names = {e.get("name") for e in trace.get("traceEvents", trace)}
    assert "world_exit" in names
    if data_mode == "files":
        shards = list((tmp_path / "shards").glob("shard-*.npz"))
        assert len(shards) == SMALL_SHARDS


@pytest.mark.slow
def test_sigterm_leaver_and_survivors_finish(coord_server, tmp_path):
    env = _worker_env(4 * EXAMPLES, 4 * SHARDS)
    env["EDL_MH_STEP_SLEEP"] = "0.04"  # keep the job alive past the TERM
    procs = {
        n: _spawn_worker(coord_server.port, n, tmp_path, 3, env,
                         tmp_path / f"{n}.log")
        for n in ("w0", "w1", "w2")
    }
    # let the 3-world actually train before scaling down
    _wait_for_line(tmp_path / "w0.log", "step 1 ", timeout_s=120)
    procs["w1"].send_signal(signal.SIGTERM)
    rcs = _wait_all(procs, timeout_s=300)
    assert rcs == {"w0": 0, "w1": 0, "w2": 0}
    assert "left at step" in (tmp_path / "w1.log").read_text()
    for n in ("w0", "w2"):
        text = (tmp_path / f"{n}.log").read_text()
        assert "done at step" in text
        assert "world=2" in text  # survivors reformed a 2-world
    _assert_exactly_once(coord_server.client(), 4 * SHARDS)


@pytest.mark.slow
@pytest.mark.parametrize("sharding", ["replicated", "fsdp"])
def test_sigkill_crash_survivors_reform_and_finish(coord_server, tmp_path,
                                                   sharding):
    """The headline fault-tolerance property: kill -9 a worker mid-world
    and the survivors must NOT die with it (round-1 regression: XLA's
    coordination service aborted the whole process; the supervised child
    quarantines the abort).  In fsdp mode the reform additionally restores
    ZeRO-3-sharded state collectively via Orbax onto the smaller world."""
    env = _worker_env(4 * EXAMPLES, 4 * SHARDS)
    env["EDL_MH_STEP_SLEEP"] = "0.04"  # keep the job alive past the kill
    extra = ("--param-sharding", sharding)
    procs = {
        n: _spawn_worker(coord_server.port, n, tmp_path, 3, env,
                         tmp_path / f"{n}.log", extra=extra)
        for n in ("w0", "w1", "w2")
    }
    _wait_for_line(tmp_path / "w0.log", "step 1 ", timeout_s=120)
    procs["w1"].kill()  # SIGKILL: no cleanup, no leave intent
    assert procs["w1"].wait(timeout=30) == -signal.SIGKILL
    del procs["w1"]
    rcs = _wait_all(procs, timeout_s=300)
    assert rcs == {"w0": 0, "w2": 0}
    for n in ("w0", "w2"):
        text = (tmp_path / f"{n}.log").read_text()
        assert "done at step" in text
        assert "world=2" in text  # reformed without the dead peer
    # the dead worker's leased shards were re-dispatched, not lost
    _assert_exactly_once(coord_server.client(), 4 * SHARDS)


@pytest.mark.slow
def test_late_joiner_inherits_trained_state(coord_server, tmp_path):
    # Throttle steps to ~25/s: the 2-world must still be mid-job ~15 s
    # later when the joiner's supervisor+child have finished forming (CPU
    # steps are sub-ms; an unthrottled queue drains before the join lands).
    env = _worker_env(4 * EXAMPLES, 4 * SHARDS)
    env["EDL_MH_STEP_SLEEP"] = "0.04"
    procs = {
        n: _spawn_worker(coord_server.port, n, tmp_path, 2, env,
                         tmp_path / f"{n}.log")
        for n in ("w0", "w1")
    }
    # wait until the 2-world has trained real steps, then scale up
    _wait_for_line(tmp_path / "w0.log", "step 20 ", timeout_s=180)
    procs["w2"] = _spawn_worker(coord_server.port, "w2", tmp_path, 1, env,
                                tmp_path / "w2.log")
    rcs = _wait_all(procs, timeout_s=300)
    assert rcs == {"w0": 0, "w1": 0, "w2": 0}
    # the joiner's first world entry must carry inherited progress: the
    # generation protocol hands it the survivors' state, never a cold start
    first_entry = _wait_for_line(tmp_path / "w2.log", "entering world",
                                 timeout_s=1)
    joined_step = int(first_entry.rsplit("step=", 1)[1])
    assert joined_step >= 20, first_entry
    assert "world=3" in (tmp_path / "w2.log").read_text()
    _assert_exactly_once(coord_server.client(), 4 * SHARDS)


def _losses(text: str) -> list:
    """[(step, loss)] from 'step N world=W loss=L' progress lines."""
    out = []
    for line in text.splitlines():
        if " loss=" in line and " step " in line:
            step = int(line.split(" step ", 1)[1].split()[0])
            out.append((step, float(line.rsplit("loss=", 1)[1])))
    return out


@pytest.mark.slow
def test_fsdp_resize_restores_sharded_state(coord_server, tmp_path):
    """BASELINE config 4 in miniature: an FSDP-sharded (ZeRO-3) model
    resizes across a world change with the sharded state persisted and
    restored COLLECTIVELY via Orbax — no single process ever holds the
    full state (role of the reference's pserver param residency,
    SURVEY §5.4, done TPU-natively).  Loss must be continuous through
    the resize: the joiner's world restores the previous generation
    instead of cold-starting."""
    env = _worker_env(4 * EXAMPLES, 4 * SHARDS)
    env["EDL_MH_STEP_SLEEP"] = "0.04"
    fsdp = ("--param-sharding", "fsdp")
    procs = {
        n: _spawn_worker(coord_server.port, n, tmp_path, 2, env,
                         tmp_path / f"{n}.log", extra=fsdp)
        for n in ("w0", "w1")
    }
    # let the 2-world make real progress, then grow it to 3
    _wait_for_line(tmp_path / "w0.log", "step 20 ", timeout_s=180)
    procs["w2"] = _spawn_worker(coord_server.port, "w2", tmp_path, 1, env,
                                tmp_path / "w2.log", extra=fsdp)
    rcs = _wait_all(procs, timeout_s=300)
    assert rcs == {"w0": 0, "w1": 0, "w2": 0}
    w2 = (tmp_path / "w2.log").read_text()
    first_entry = _wait_for_line(tmp_path / "w2.log", "entering world",
                                 timeout_s=1)
    joined_step = int(first_entry.rsplit("step=", 1)[1])
    assert joined_step >= 20, first_entry  # inherited, not cold-started
    assert "world=3" in w2
    # loss continuity: every loss in the resized world is below the
    # cold-start loss of the original world (state survived the reshard)
    cold = _losses((tmp_path / "w0.log").read_text())[0]
    assert cold[0] == 1
    post = [l for s, l in _losses(w2)]
    assert post and max(post) < cold[1], (cold, post)
    _assert_exactly_once(coord_server.client(), 4 * SHARDS)


@pytest.mark.slow
@pytest.mark.parametrize("sharding", ["replicated", "fsdp"])
def test_transformer_sigkill_crash_reform(coord_server, tmp_path, sharding):
    """The REAL model family through the supervised crash path (round-3
    verdict missing #1): the GQA decoder the bench measures (RMSNorm /
    RoPE / GQA attention / SwiGLU, edl_tpu.models.transformer TINY) — not
    the synthetic MLP — trains next-token prediction across 3 workers;
    kill -9 one mid-world; the survivors reform a 2-world, restore the
    newest MID-WORLD generation onto the smaller mesh (collective Orbax
    in fsdp mode, npz in replicated mode — publish_mid_state bounds the
    crash loss to the checkpoint cadence), keep the loss continuous
    through the reform, and drain the queue exactly-once.  Reference
    analogue: example/train_ft.py:105-114 runs its real model through FT;
    its pserver param residency is why a trainer crash lost no state —
    the mid-world generation is the TPU-native equivalent.
    """
    # enough rows that the job is still mid-training long after the
    # reform (~340 steps at world 2), so post-reform loss lines exist
    env = _worker_env(12288, 48)
    env.update(EDL_MH_MODEL="transformer", EDL_MH_SEQ="32",
               EDL_MH_BATCH="16", EDL_MH_STEP_SLEEP="0.05",
               EDL_MH_CKPT_EVERY="20")
    extra = ("--model", "transformer", "--model-config", "tiny",
             "--param-sharding", sharding)
    procs = {
        n: _spawn_worker(coord_server.port, n, tmp_path, 3, env,
                         tmp_path / f"{n}.log", extra=extra)
        for n in ("w0", "w1", "w2")
    }
    # the 3-world trains past the step-20 mid-world checkpoint (step 40
    # in the log means the step-20 publish is long since durable)
    _wait_for_line(tmp_path / "w0.log", "step 40 ", timeout_s=240)
    procs["w1"].kill()  # SIGKILL mid-step: no cleanup, no leave intent
    assert procs["w1"].wait(timeout=30) == -signal.SIGKILL
    del procs["w1"]
    rcs = _wait_all(procs, timeout_s=600)
    assert rcs == {"w0": 0, "w2": 0}

    w0 = (tmp_path / "w0.log").read_text()
    assert "done at step" in w0
    assert "world=2" in w0  # reformed without the dead peer

    # the reform RESTORED trained state onto the smaller mesh: the second
    # world entry carries the crash-surviving generation's step, not 0
    entries = [l for l in w0.splitlines() if "entering world" in l]
    assert len(entries) >= 2, entries
    resumed_step = int(entries[1].rsplit("step=", 1)[1])
    assert resumed_step >= 20, entries[1]

    # loss continuity on the real architecture: next-token CE starts near
    # ln(vocab)≈5.5 cold; every post-reform loss must stay below the
    # cold-start loss (a silent re-init would jump back to ~5.5)
    losses = _losses(w0)
    cold_step, cold_loss = losses[0]
    assert cold_step == 1
    post_reform = [l for s, l in losses if s > resumed_step]
    assert post_reform and max(post_reform) < cold_loss, (
        cold_loss, post_reform[:5])
    # and it actually LEARNED the successor task (not just noise)
    assert min(post_reform) < cold_loss / 2, (cold_loss, min(post_reform))

    _assert_exactly_once(coord_server.client(), 48)


@pytest.mark.slow
def test_harness_sigkill_reaps_worker_tree_and_coord(tmp_path):
    """Suite interruption safety (round-3 verdict weak #5): a harness that
    spawned a coord server (spawn_server) and a worker supervisor
    (EDL_MH_DIE_WITH_PARENT) dying by SIGKILL — no cleanup code runs —
    must leave zero stray processes: the PDEATHSIG chain reaps
    harness → coord server, harness → supervisor → world child."""
    harness_body = r"""
import os, subprocess, sys, time
from edl_tpu.coord.server import spawn_server

h = spawn_server(member_ttl_ms=3000, task_timeout_ms=4000)
env = dict(os.environ, JAX_PLATFORMS="cpu",
           XLA_FLAGS="--xla_force_host_platform_device_count=1",
           EDL_MH_EXAMPLES="16384", EDL_MH_SHARDS="64",
           EDL_MH_STEP_SLEEP="0.05", EDL_MH_DIE_WITH_PARENT="1")
w = subprocess.Popen(
    [sys.executable, "-m", "edl_tpu.runtime.multihost_worker",
     "--coord", f"127.0.0.1:{h.port}", "--name", "w0",
     "--ckpt-dir", sys.argv[1], "--min-members", "1", "--settle-s", "0.2"],
    env=env)
print(f"PIDS {h.process.pid} {w.pid}", flush=True)
time.sleep(300)
"""
    harness = subprocess.Popen(
        [sys.executable, "-c", harness_body, str(tmp_path)],
        stdout=subprocess.PIPE, text=True)
    try:
        line = harness.stdout.readline()
        assert line.startswith("PIDS "), line
        coord_pid, worker_pid = map(int, line.split()[1:])

        def alive(pid):
            try:
                os.kill(pid, 0)
                return True
            except ProcessLookupError:
                return False

        assert alive(coord_pid) and alive(worker_pid)
        # give the supervisor a moment to start its tree (the deathsig
        # chain covers whatever exists at kill time, child or not)
        time.sleep(2)

        harness.kill()  # SIGKILL: no atexit, no finally, nothing
        harness.wait(timeout=10)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and (
                alive(coord_pid) or alive(worker_pid)):
            time.sleep(0.25)
        assert not alive(coord_pid), "coord server orphaned"
        assert not alive(worker_pid), "worker supervisor orphaned"
    finally:
        if harness.poll() is None:
            harness.kill()


@pytest.mark.slow
@pytest.mark.parametrize("warm", ["1", "0"])
def test_warm_respawn_knob_observed_in_supervisor_log(coord_server, tmp_path,
                                                      warm):
    """The warm pre-spawn actually serves reforms (and its kill switch
    works): the supervisor's world-start trace event records warm=True
    when the plan was piped to a pre-spawned child, warm=False under
    EDL_MH_WARM_SPAWN=0 — so a silent regression to cold spawns (which
    only degrades latency, never correctness) fails here (review r4)."""
    env = _worker_env(8192, 32)
    env.update(EDL_MH_STEP_SLEEP="0.05", EDL_MH_WARM_SPAWN=warm,
               EDL_MH_TRACE=str(tmp_path / "traces"))
    procs = {"w0": _spawn_worker(coord_server.port, "w0", tmp_path, 1, env,
                                 tmp_path / "w0.log")}
    # world 1 lives well past the respawn delay before w1's join reforms it
    _wait_for_line(tmp_path / "w0.log", "step 60 ", timeout_s=180)
    procs["w1"] = _spawn_worker(coord_server.port, "w1", tmp_path, 1, env,
                                tmp_path / "w1.log")
    rcs = _wait_all(procs, timeout_s=300)
    assert rcs == {"w0": 0, "w1": 0}
    import json as _json

    trace = _json.loads((tmp_path / "traces" / "trace-w0.json").read_text())
    starts = [e for e in trace.get("traceEvents", trace)
              if e.get("name") == "world_start"]
    assert len(starts) >= 2, starts
    by_epoch = {e["args"]["epoch"]: e["args"]["warm"] for e in starts}
    if warm == "1":
        assert by_epoch[2] is True, by_epoch
    else:
        assert all(v is False for v in by_epoch.values()), by_epoch
    _assert_exactly_once(coord_server.client(), 32)


@pytest.mark.slow
def test_multi_device_hosts_form_one_mesh(coord_server, tmp_path):
    """Multi-chip hosts: each worker PROCESS holds several devices (the
    TPU pod reality — one process per host, 4-8 chips each), so the
    world's mesh is processes × local devices and the per-process flag
    rows must tile evenly over P('dp') (train_world sizes them by
    jax.local_device_count).  Two 2-device processes train to completion
    with exactly-once accounting — the path single-device tests miss."""
    env = _worker_env(SMALL_EXAMPLES, SMALL_SHARDS)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = {
        n: _spawn_worker(coord_server.port, n, tmp_path, 2, env,
                         tmp_path / f"{n}.log")
        for n in ("w0", "w1")
    }
    rcs = _wait_all(procs, timeout_s=240)
    assert rcs == {"w0": 0, "w1": 0}
    for n in procs:
        text = (tmp_path / f"{n}.log").read_text()
        assert "done at step" in text
        assert "world=2" in text  # 2 processes (4 devices total)
    _assert_exactly_once(coord_server.client(), SMALL_SHARDS)


@pytest.mark.slow
def test_stalled_world_child_killed_by_watchdog_and_epoch_rebuilds(
        coord_server, tmp_path):
    """THE quiet-failure acceptance drill: one worker's train loop wedges
    mid-step (no crash, no closed socket — its supervisor and lease
    renewals stay perfectly healthy).  Nothing in the crash path can see
    it; the supervisor's StallWatchdog must: detect the missing progress
    beats within the EWMA deadline, SIGKILL the wedged child (turning the
    silent hang into the already-handled death), and let the epoch
    rebuild.  Both workers finish the job with exactly-once accounting —
    and the detection latency recorded in the log is within 2× the
    deadline in force at the breach."""
    import re

    env = _worker_env(EXAMPLES, SHARDS)
    # steps SLOWER than the supervisor's 0.1 s heartbeat poll so several
    # distinct beats are observed and the EWMA settles before the wedge
    env["EDL_MH_STEP_SLEEP"] = "0.1"
    env["EDL_MH_STALL"] = "w1:12"      # w1 wedges (forever) after step 12
    extra = ("--stall-floor-s", "3", "--stall-k", "6")
    procs = {
        n: _spawn_worker(coord_server.port, n, tmp_path, 2, env,
                         tmp_path / f"{n}.log", extra=extra)
        for n in ("w0", "w1")
    }
    # the injection actually happened (not a vacuous pass)
    _wait_for_line(tmp_path / "w1.log", "injecting stall", timeout_s=180)
    # the watchdog saw it: silence crossed the deadline, child killed
    line = _wait_for_line(tmp_path / "w1.log", "stall detected",
                          timeout_s=120)
    m = re.search(r"silent_s=([0-9.]+) deadline_s=([0-9.]+)", line)
    assert m, line
    silent_s, deadline_s = float(m.group(1)), float(m.group(2))
    assert deadline_s >= 3.0  # the floor ruled (EWMA steps are ~40 ms)
    assert silent_s <= 2 * deadline_s, line  # the acceptance bound
    rcs = _wait_all(procs, timeout_s=420)
    assert rcs == {"w0": 0, "w1": 0}
    w1_log = (tmp_path / "w1.log").read_text()
    # the kill became a reform: the supervisor treated the stall as the
    # crash it already knows, and the job then drained to completion
    assert "world child died; reforming" in w1_log
    for n in ("w0", "w1"):
        assert "done at step" in (tmp_path / f"{n}.log").read_text()
    # exactly-once accounting across the stall + reform: the wedged
    # child's leased shard re-dispatched, nothing double-counted
    _assert_exactly_once(coord_server.client(), SHARDS)
