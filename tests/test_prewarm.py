"""Speculative mesh prewarm: compile off the hot path, race-proof.

PR 3's perf tentpole: ElasticTrainer.prewarm compiles neighbor mesh
bundles on a background thread so resize() pays only the reshard hop.
These tests pin the contracts the speculation must keep: the classic
prewarm/resize race (a resize of a size that is mid-compile waits for
that compile instead of duplicating it), hints for sizes that never
arrive stay bounded, and the transactional-rollback guarantee survives
a staged bundle that came from the prewarm thread.
"""

from __future__ import annotations

import time

import jax
import numpy as np
import optax
import pytest

import edl_tpu.runtime.elastic as elastic_mod
from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.parallel.mesh import MeshSpec
from edl_tpu.runtime.elastic import ElasticTrainer

BATCH = 64


def make_trainer(**kw):
    params = mlp.init(jax.random.key(0), [16, 32, 4])
    return ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                          spec=MeshSpec(dp=-1), initial_world_size=2, **kw)


def batch():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(BATCH, 16)).astype(np.float32)
    y = rng.integers(0, 4, BATCH).astype(np.int32)
    return x, y


def test_prewarm_hit_skips_compile():
    tr = make_trainer()
    b = batch()
    for _ in range(2):
        tr.step(b)  # teaches the trainer the batch shape for AOT
    t = tr.prewarm([4], wait=True)
    assert t is not None
    assert tr.resize(4)
    evt = tr.resize_events[-1]
    assert evt["prewarm_hit"] is True
    # the compile happened on the prewarm thread: the resize's bundle
    # acquisition is a cache hit, orders of magnitude under a jit compile
    assert evt["compile_ms"] < 50.0, evt
    # and the first step on the new mesh runs the AOT executable
    t0 = time.perf_counter()
    loss = tr.step(b)
    first_step_ms = (time.perf_counter() - t0) * 1000
    assert np.isfinite(loss)
    assert first_step_ms < 200.0, first_step_ms


def test_resize_mid_prewarm_waits_not_duplicates():
    """A resize landing while its size is still compiling on the prewarm
    thread must finish that compile (pay the residual), not race a second
    compile or commit a half-built bundle."""
    tr = make_trainer()
    b = batch()
    tr.step(b)
    before = get_counters().get("mesh_prewarms")
    tr.prewarm([4])  # no wait: compile in flight
    assert tr.resize(4)  # lands mid-compile
    assert tr.world_size == 4
    assert np.isfinite(tr.step(b))
    evt = tr.resize_events[-1]
    # speculation was in flight → counted as a hit, whatever the residual
    assert evt["prewarm_hit"] is True
    # exactly one bundle exists for the size (no duplicate compile)
    key = tr._cache_key(4)
    assert key in tr._step_cache and not tr._building
    assert get_counters().get("mesh_prewarms") <= before + 1


def test_unused_hints_are_bounded():
    """Hints for sizes that never arrive must not grow the executable
    cache without bound: beyond prewarm_cache_limit the oldest unused
    speculative bundle is evicted."""
    tr = make_trainer(prewarm_cache_limit=2)
    tr.step(batch())
    for n in (3, 4, 5, 6, 7):  # five hints, none ever resized to
        tr.prewarm([n], wait=True)
    speculative = [k for k, v in tr._step_cache.items()
                   if v.source == "prewarm"]
    assert len(speculative) <= 2, speculative
    assert len(tr._prewarm_unused) <= 2
    assert get_counters().get("prewarms_evicted") >= 3


def test_used_prewarm_bundle_exempt_from_eviction():
    tr = make_trainer(prewarm_cache_limit=1)
    tr.step(batch())
    tr.prewarm([4], wait=True)
    assert tr.resize(4)  # graduates the speculative bundle to live
    live_bundle = tr._step_cache[tr._cache_key(4)]
    tr.prewarm([5], wait=True)
    tr.prewarm([6], wait=True)  # eviction pressure
    assert tr._step_cache[tr._cache_key(4)] is live_bundle


def test_rollback_clean_with_prewarmed_bundle(monkeypatch):
    """The transactional-resize guarantee must hold when the staged
    bundle came from the prewarm thread: a reshard failure rolls back to
    the previous mesh and the trainer keeps stepping."""
    tr = make_trainer()
    b = batch()
    tr.step(b)
    tr.prewarm([4], wait=True)
    real_reshard = elastic_mod._reshard

    def boom(tree, shardings):
        raise RuntimeError("injected reshard OOM")

    monkeypatch.setattr(elastic_mod, "_reshard", boom)
    assert tr.resize(4) is False
    assert tr.world_size == 2
    assert tr.resizes_failed == 1
    monkeypatch.setattr(elastic_mod, "_reshard", real_reshard)
    assert np.isfinite(tr.step(b))  # previous world fully intact
    # the prewarmed bundle survived the rollback: the retry is a pure hit
    assert tr.resize(4)
    assert tr.resize_events[-1]["prewarm_hit"] is True
    assert np.isfinite(tr.step(b))


def test_prewarm_skips_invalid_and_current_sizes():
    tr = make_trainer()
    assert tr.prewarm([0, -1, 10_000, tr.world_size, None]) is None


def test_resize_events_record_split():
    tr = make_trainer()
    b = batch()
    tr.step(b)
    assert tr.resize(4)  # cold: inline compile
    evt = tr.resize_events[-1]
    assert set(evt) >= {"size", "compile_ms", "reshard_ms", "prewarm_hit",
                        "step"}
    assert evt["prewarm_hit"] is False
    assert evt["compile_ms"] > evt["reshard_ms"], evt


@pytest.mark.parametrize("sizes", [(4, 8), (8, 4)])
def test_oscillation_still_correct_with_prewarm(sizes):
    """Grow/shrink through prewarmed sizes keeps learning (the PR 2
    stale-mesh regression surface, now with speculation in the mix)."""
    tr = make_trainer()
    b = batch()
    losses = [tr.step(b) for _ in range(3)]
    for n in sizes + (2,):
        tr.prewarm([n], wait=True)
        assert tr.resize(n)
        losses += [tr.step(b) for _ in range(3)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0] * 2.0  # no blow-up across the dance
