"""The SDC defense plane (edl_tpu.runtime.sdc): silent-data-corruption
detection and repair.

The acceptance property (ISSUE 17 / doc/sdc_defense.md): a training run
struck by a silent corruption — a flipped gradient bit, a flipped live
parameter bit — detects it (fingerprint cross-check or loss-anomaly
gate), confirms it against an independent shadow recomputation, rolls
back to the last VERIFIED checkpoint, quarantines the suspect worker,
and replays through the virtual-worker cursors so the stitched
trajectory is BITWISE-IDENTICAL to an uninjected control.  A poisoned
metric over clean parameters must be REFUTED, not rolled back.

Also home to: the fingerprint/fold primitives, the dp cross-check
minority vote, the verified-lineage manifest bits (checkpoint v3), the
quarantine marker's keepalive/amnesty contract, and the seeded SDC
fault-plan determinism.
"""

from __future__ import annotations

import json
import math

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import optax  # noqa: E402

from edl_tpu.coord import PyCoordService, local_service  # noqa: E402
from edl_tpu.models import mlp  # noqa: E402
from edl_tpu.observability.collector import get_counters  # noqa: E402
from edl_tpu.parallel.mesh import MeshSpec  # noqa: E402
from edl_tpu.runtime.checkpoint import ElasticCheckpointer  # noqa: E402
from edl_tpu.runtime.data import ShardRegistry  # noqa: E402
from edl_tpu.runtime.elastic import ElasticTrainer  # noqa: E402
from edl_tpu.runtime.sdc import (  # noqa: E402
    AnomalyDetector,
    SdcPlane,
    ShadowRecompute,
    UpdateFingerprinter,
    clear_quarantine,
    flip_tree_bit,
    fold_fingerprint,
    quarantine_worker,
    quarantined_names,
    tree_fingerprint,
    tree_leaf_folds,
)
from edl_tpu.runtime.virtual import (  # noqa: E402
    VirtualBatches,
    VirtualConfig,
    VirtualWorkerLoop,
)

SEED = 3
CFG = VirtualConfig(vw_count=8, global_batch=64, job_seed=SEED)
STEPS = 14


def _dataset(n=2048):
    rng = np.random.default_rng(1)
    y = rng.integers(0, 4, n).astype(np.int32)
    x = rng.normal(size=(n, 16)).astype(np.float32)
    return x, y


def _batches():
    reg = ShardRegistry()
    ids = reg.register_arrays(_dataset(), num_shards=16)
    return VirtualBatches(CFG, ids, reg.get, passes=2)


def _trainer(world=1):
    params = mlp.init(jax.random.key(0), [16, 32, 4])
    return ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                          spec=MeshSpec(dp=-1), initial_world_size=world,
                          accum_mode="replicated")


@pytest.fixture(scope="module")
def control():
    """The uninjected reference trajectory every drill compares against."""
    return VirtualWorkerLoop(_trainer(), CFG, _batches()).run(max_steps=STEPS)


def _plane(ck=None, kv=None, job="job", worker="w0", flight_dir=None):
    shadow = ShadowRecompute(_trainer, _batches, CFG, checkpointer=ck)
    return SdcPlane(
        fingerprinter=UpdateFingerprinter(kv=kv, job=job, worker=worker),
        detector=AnomalyDetector(), shadow=shadow, checkpointer=ck,
        flight_dir=flight_dir)


# ---------------------------------------------------------------------------
# fingerprint primitives
# ---------------------------------------------------------------------------

class TestFingerprintPrimitives:
    def test_tree_fingerprint_deterministic_and_bit_sensitive(self):
        t = {"w": np.arange(64, dtype=np.float32),
             "b": {"c": np.ones((4, 4), np.float64)}}
        fp = tree_fingerprint(t)
        assert fp == tree_fingerprint(t)  # pure
        assert len(fp) == 16 and int(fp, 16) >= 0
        for leaf in range(2):
            flipped = tree_fingerprint(flip_tree_bit(t, leaf=leaf, bit=0))
            assert flipped != fp  # ONE flipped bit anywhere changes it

    def test_flip_is_an_involution_and_copies(self):
        t = {"w": np.arange(8, dtype=np.float32)}
        before = t["w"].copy()
        once = flip_tree_bit(t, leaf=0, bit=3)
        assert np.array_equal(t["w"], before)  # original untouched
        twice = flip_tree_bit(once, leaf=0, bit=3)
        assert tree_fingerprint(twice) == tree_fingerprint(t)

    def test_fold_is_dtype_and_shape_sensitive(self):
        a = {"x": np.zeros(4, np.float32)}
        b = {"x": np.zeros(4, np.float64)}
        c = {"x": np.zeros(8, np.float32)}
        fps = {tree_fingerprint(a), tree_fingerprint(b), tree_fingerprint(c)}
        assert len(fps) == 3  # same bytes-ish content, all distinguished

    def test_fold_fingerprint_is_path_keyed(self):
        # the same leaf folds under different paths must not collide by
        # commuting — the combiner is order-fixed over sorted paths
        f1 = fold_fingerprint({"a": 1, "b": 2})
        f2 = fold_fingerprint({"a": 2, "b": 1})
        assert f1 != f2

    def test_tree_leaf_folds_cover_every_leaf(self):
        t = {"w": np.ones(4, np.float32), "b": {"c": np.ones(2, np.int32)}}
        folds = tree_leaf_folds(t)
        assert len(folds) == 2
        assert all(isinstance(v, int) for v in folds.values())


# ---------------------------------------------------------------------------
# anomaly gate
# ---------------------------------------------------------------------------

class TestAnomalyDetector:
    def test_clean_stream_never_trips(self):
        det = AnomalyDetector()
        rng = np.random.default_rng(0)
        for _ in range(200):
            assert det.observe(1.5 + 0.05 * rng.standard_normal()) is None

    def test_nan_and_inf_always_trip(self):
        det = AnomalyDetector()
        assert det.observe(float("nan")) == "nan"
        assert det.observe(float("inf")) == "nan"

    def test_spike_trips_after_warmup(self):
        det = AnomalyDetector(z=6.0, warmup=8)
        for i in range(20):
            det.observe(1.0 + 0.01 * math.sin(i))
        assert det.observe(3.0) == "loss_spike"

    def test_explosion_trips_even_during_warmup(self):
        det = AnomalyDetector(warmup=8)
        det.observe(1.8)
        assert det.observe(8.5e36) == "loss_spike"

    def test_anomaly_not_folded_into_baseline(self):
        det = AnomalyDetector(z=6.0, warmup=4)
        for i in range(10):
            det.observe(1.0 + 0.01 * math.sin(i))
        assert det.observe(50.0) == "loss_spike"
        # the spike did NOT teach the detector that 50 is normal
        assert det.observe(50.0) == "loss_spike"
        assert det.observe(1.0) is None


# ---------------------------------------------------------------------------
# dp cross-check
# ---------------------------------------------------------------------------

class TestCrossCheck:
    def _fp(self, kv, job, worker, cadence=1):
        return UpdateFingerprinter(kv=kv, job=job, worker=worker,
                                   cadence=cadence)

    def test_majority_names_the_minority(self):
        kv = PyCoordService()
        t = {"w": np.ones(4, np.float32)}
        bad = flip_tree_bit(t, bit=5)
        for worker, tree in (("w0", t), ("w1", t), ("w2", bad)):
            self._fp(kv, "j", worker).record(3, tree)
        check = self._fp(kv, "j", "w0").cross_check(3)
        assert check.mismatch and check.suspects == ["w2"]

    def test_even_split_is_mismatch_without_suspects(self):
        kv = PyCoordService()
        t = {"w": np.ones(4, np.float32)}
        self._fp(kv, "j", "w0").record(3, t)
        self._fp(kv, "j", "w1").record(3, flip_tree_bit(t, bit=5))
        check = self._fp(kv, "j", "w0").cross_check(3)
        assert check.mismatch and check.suspects == []

    def test_agreement_and_singleton(self):
        kv = PyCoordService()
        t = {"w": np.ones(4, np.float32)}
        fp0 = self._fp(kv, "j", "w0")
        fp0.record(3, t)
        assert fp0.cross_check(3) is None  # alone: nothing to check
        self._fp(kv, "j", "w1").record(3, t)
        check = fp0.cross_check(3)
        assert check is not None and not check.mismatch

    def test_cadence_skips_off_steps(self):
        fp = UpdateFingerprinter(cadence=5)
        t = {"w": np.ones(4, np.float32)}
        assert fp.record(3, t) is None
        assert fp.record(5, t) is not None
        assert get_counters().get("sdc_fingerprints") >= 1


# ---------------------------------------------------------------------------
# verified lineage (checkpoint manifest v3)
# ---------------------------------------------------------------------------

class TestVerifiedLineage:
    def _tree(self, step):
        return {"w": np.arange(64, dtype=np.float32) * (step + 1),
                "b": np.ones((8,), np.float32) * step}

    def test_sync_save_writes_verified_manifest(self, tmp_path):
        ck = ElasticCheckpointer(tmp_path / "ck")
        ck.save(1, self._tree(1))
        m = ck.manifest(1)
        assert m["version"] == 3 and m["verified"] is True
        assert m["tree_hash"] == tree_fingerprint(self._tree(1))
        assert set(m["leaves"]) == set(tree_leaf_folds(self._tree(1)))
        assert ck.manifest_verified(1) is True
        ck.close()

    def test_async_save_verifies_at_finalize(self, tmp_path):
        ck = ElasticCheckpointer(tmp_path / "ck")
        ck.save_async(2, self._tree(2))
        ck.finalize()
        assert ck.manifest_verified(2) is True
        assert ck.manifest(2)["tree_hash"] == tree_fingerprint(self._tree(2))
        ck.close()

    def test_forged_manifest_reads_unverified(self, tmp_path):
        ck = ElasticCheckpointer(tmp_path / "ck")
        ck.save(1, self._tree(1))
        mpath = ck._manifest_path(1)
        m = json.loads(mpath.read_text())
        del m["verified"]
        mpath.write_text(json.dumps(m))
        assert ck.manifest_verified(1) is False
        ck.close()

    def test_verify_restored_spot_checks_shared_leaves(self, tmp_path):
        ck = ElasticCheckpointer(tmp_path / "ck")
        ck.save(1, self._tree(1))
        good = ck.restore(self._tree(0), step=1)
        assert ck.verify_restored(1, good) is True
        assert ck.last_restore_hash_ok is True
        bad = dict(good)
        bad["w"] = np.asarray(flip_tree_bit({"w": good["w"]}, bit=9)["w"])
        assert ck.verify_restored(1, bad) is False
        # a PARTIAL tree verifies its shared subset only
        assert ck.verify_restored(1, {"b": good["b"]}) is True
        assert ck.verify_restored(1, {"zzz": good["b"]}) is None
        ck.close()

    def test_restore_falls_back_past_hash_forged_step(self, tmp_path):
        """Files intact + CRCs matching + Orbax parsing — but the
        manifest's leaf hashes disagree with what was parsed (a forged
        manifest around substituted data).  restore() must fall back to
        the previous verified step and count the detection."""
        ck = ElasticCheckpointer(tmp_path / "ck", max_to_keep=4)
        ck.save(1, self._tree(1))
        ck.save(2, self._tree(2))
        mpath = ck._manifest_path(2)
        m = json.loads(mpath.read_text())
        first = sorted(m["leaves"])[0]
        m["leaves"][first] = f"{0:016x}"  # lie about one leaf
        mpath.write_text(json.dumps(m))
        before = get_counters().get("checkpoint_tree_hash_mismatch")
        restored = ck.restore(self._tree(0))
        assert np.array_equal(restored["w"], self._tree(1)["w"])  # fell back
        assert ck.last_restored_step == 1
        assert get_counters().get("checkpoint_tree_hash_mismatch") == before + 1
        ck.close()


# ---------------------------------------------------------------------------
# quarantine protocol (PR 2 eviction contract, SDC flavor)
# ---------------------------------------------------------------------------

class TestQuarantine:
    def test_marker_declines_rejoin_and_amnesty_lifts_it(self):
        from edl_tpu.runtime.multihost import ElasticWorld

        coord = PyCoordService()
        healthy = ElasticWorld(coord, "w0")
        healthy.join()
        assert quarantine_worker(coord, "w1", reason="sdc step 9")
        assert "w1" in quarantined_names(coord)
        # membership machinery sees it exactly like an eviction
        assert "w1" in healthy.evicted_names()
        # the fresh incarnation's first act lifts its own marker
        reborn = ElasticWorld(coord, "w1", settle_s=0.05, poll_s=0.01)
        assert reborn.clear_eviction() is True
        assert "w1" not in quarantined_names(coord)
        reborn.join()
        _, names = reborn.wait_stable(min_members=2, timeout_s=5.0)
        assert "w1" in names

    def test_clear_quarantine_idempotent(self):
        kv = PyCoordService()
        quarantine_worker(kv, "w9")
        assert clear_quarantine(kv, "w9") is True
        assert clear_quarantine(kv, "w9") is False

    def test_fp_keys_are_job_swept_markers_are_not(self):
        from edl_tpu.coord.gc import JOB_KV_PREFIXES, gc_job_kv

        assert "sdc-fp/" in JOB_KV_PREFIXES
        kv = PyCoordService()
        kv.kv_set("sdc-fp/j/5/w0", b"x")
        quarantine_worker(kv, "w0")
        assert gc_job_kv(kv, "j") == 1
        assert kv.kv_get("sdc-fp/j/5/w0") is None
        assert "w0" in quarantined_names(kv)  # per-worker: survives the job


# ---------------------------------------------------------------------------
# seeded SDC fault plans
# ---------------------------------------------------------------------------

class TestSdcFaultPlans:
    def test_kinds_registered_and_frozen(self):
        from edl_tpu.runtime.faults import ACTION_TYPES, SDC_KINDS

        assert SDC_KINDS == ("corrupt_gradient", "flip_param_bits",
                             "poison_loss")
        for kind in SDC_KINDS:
            assert kind in ACTION_TYPES

    def test_seeded_plan_is_deterministic(self):
        from edl_tpu.runtime.faults import FaultPlan, SDC_KINDS

        a = FaultPlan.random(11, n_faults=3, kinds=SDC_KINDS)
        b = FaultPlan.random(11, n_faults=3, kinds=SDC_KINDS)
        assert a.describe() == b.describe()
        assert {d["kind"] for d in a.describe()} == set(SDC_KINDS)

    def test_actions_require_a_trainer_in_ctx(self):
        from edl_tpu.runtime.faults import CorruptGradient, FaultContext

        with pytest.raises(RuntimeError, match="trainer"):
            CorruptGradient().fire(FaultContext())


# ---------------------------------------------------------------------------
# the drills: detect → shadow → rollback → bitwise replay
# ---------------------------------------------------------------------------

class TestEndToEndDrills:
    def test_flip_param_bits_confirmed_rolled_back_bitwise(
            self, tmp_path, control):
        """Drill 1 (single worker): a live parameter bit flip explodes
        the next loss → anomaly gate → shadow recompute from the last
        verified checkpoint CONFIRMS → rollback + cursor replay.  The
        final trajectory is bitwise-identical to the uninjected
        control, the ledger balances, and the flight record carries the
        verdict trail."""
        ck = ElasticCheckpointer(tmp_path / "ck")
        tr = _trainer()
        plane = _plane(ck=ck, flight_dir=str(tmp_path / "fr"))
        loop = VirtualWorkerLoop(tr, CFG, _batches(), checkpointer=ck,
                                 ckpt_every=5, sdc=plane)
        fired = []

        def strike(step, loss, world):
            if step == 7 and not fired:
                fired.append(step)
                tr.flip_param_bits(leaf=0, bit=30)

        rep = loop.run(max_steps=STEPS, on_step=strike)
        assert rep.rollbacks == 1
        conf = [v for v in plane.verdicts if v.outcome == "confirmed"]
        assert conf and conf[0].rollback_step == 5
        assert not plane.healthy()
        assert rep.losses == control.losses  # BITWISE continuity
        assert rep.rows_trained == control.rows_trained  # exactly-once held
        recs = list((tmp_path / "fr").glob("*.json"))
        assert recs
        payload = json.loads(recs[0].read_text())["extra"]
        assert payload["sdc"]["outcome"] == "confirmed"
        assert payload["sdc"]["trigger"] in ("loss_spike", "nan")
        trail = payload["sdc_verdict_trail"]
        assert trail[-1]["rollback_step"] == 5
        ck.close()

    def test_corrupt_gradient_cross_checked_and_quarantined(
            self, tmp_path, control):
        """Drill 2 (two dp workers in lock-step): one worker's
        accumulated gradient is corrupted pre-apply.  Its published
        fingerprint splits from its peer's; the shadow recomputation
        breaks the 2-way tie, names the corrupt worker, quarantines it,
        and rolls it back — BOTH workers end bitwise-equal to the
        control, and the fired CorruptGradient fault's recovery
        predicate observes the rollback."""
        from edl_tpu.runtime.faults import (CorruptGradient, FaultContext,
                                            FaultPlan, FaultPlanEngine)

        kv = local_service()
        rigs = {}
        for worker in ("wA", "wB"):
            ck = ElasticCheckpointer(tmp_path / worker)
            tr = _trainer()
            plane = _plane(ck=ck, kv=kv, job="drill2", worker=worker)
            loop = VirtualWorkerLoop(tr, CFG, _batches(), checkpointer=ck,
                                     ckpt_every=5, sdc=plane)
            rigs[worker] = (tr, loop, plane, ck)
        # the corruption strikes wB through the seeded fault engine
        plan = FaultPlan(actions=[CorruptGradient(at_step=7)], seed=SEED)
        ctx = FaultContext()
        ctx.trainer = rigs["wB"][0]
        engine = FaultPlanEngine(plan, ctx)
        for i in range(1, STEPS + 1):
            engine(i)
            rigs["wA"][1].run(max_steps=i)
            rigs["wB"][1].run(max_steps=i)
        _, loopA, planeA, ckA = rigs["wA"]
        _, loopB, planeB, ckB = rigs["wB"]
        conf = [v for v in planeB.verdicts if v.outcome == "confirmed"]
        assert conf and conf[0].trigger == "fp_mismatch"
        assert conf[0].quarantined == "wB"
        assert "wB" in quarantined_names(kv)
        assert loopB.report.rollbacks == 1
        assert loopA.report.rollbacks == 0  # the honest peer never rolls
        assert loopB.report.losses == control.losses
        assert loopA.report.losses == control.losses
        assert engine.quiescent() and engine.recovered == ["corrupt_gradient"]
        clear_quarantine(kv, "wB")
        ckA.close()
        ckB.close()

    def test_poison_loss_refuted_and_metric_repaired(self, control):
        """Drill 3: a NaN loss REPORT over clean parameters.  The
        shadow recompute refutes it (the honest recomputation matches
        the live fingerprint), nothing rolls back, no one is
        quarantined — and the recorded trajectory carries the repaired
        honest loss, bitwise-equal to control."""
        from edl_tpu.runtime.faults import (FaultContext, FaultPlanEngine,
                                            PoisonLoss, FaultPlan)

        tr = _trainer()
        plane = _plane()
        loop = VirtualWorkerLoop(tr, CFG, _batches(), sdc=plane)
        plan = FaultPlan(actions=[PoisonLoss(at_step=6)], seed=SEED)
        ctx = FaultContext()
        ctx.trainer = tr
        engine = FaultPlanEngine(plan, ctx)
        before = get_counters().get("sdc_losses_repaired")
        rep = loop.run(max_steps=STEPS, on_step=engine)
        ref = [v for v in plane.verdicts if v.outcome == "refuted"]
        assert ref and ref[0].trigger == "nan"
        assert rep.rollbacks == 0
        assert plane.healthy()  # a refuted episode is not ill health
        assert rep.losses == control.losses
        assert get_counters().get("sdc_losses_repaired") == before + 1
        assert engine.quiescent() and engine.recovered == ["poison_loss"]
