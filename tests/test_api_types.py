"""Resource-model tests: helpers + validation/defaulting parity
(reference pkg/resource/training_job_test.go:27-46, pkg/jobparser.go:47-71)."""

import pytest

from edl_tpu.api import (
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
    TpuTopology,
    ValidationError,
    set_defaults_and_validate,
)
from edl_tpu.api.types import DEFAULT_IMAGE, DEFAULT_PORT, RESOURCE_TPU


def mk(min_i=1, max_i=1, ft=False, tpu="0", topology=None, name="j"):
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=ft,
            trainer=TrainerSpec(
                min_instance=min_i,
                max_instance=max_i,
                topology=topology,
                resources=ResourceRequirements(limits={RESOURCE_TPU: tpu}),
            ),
        ),
    )


def test_need_tpu():
    # reference training_job_test.go:27-37 (NeedGPU → need_tpu)
    assert not mk(tpu="0").need_tpu()
    assert mk(tpu="1").need_tpu()


def test_elastic():
    # reference training_job_test.go:39-46
    assert mk(1, 2, ft=True).elastic()
    assert not mk(2, 2).elastic()


def test_topology_chips():
    t = TpuTopology.parse("2x2x1")
    assert t.chips == 4
    assert str(t) == "2x2x1"
    job = mk(topology=t)
    assert job.tpu_chips_per_trainer() == 4
    assert job.need_tpu()


def test_defaults():
    # reference jobparser.go:49-64
    job = set_defaults_and_validate(mk())
    assert job.spec.port == DEFAULT_PORT
    assert job.spec.ports_num == 1
    assert job.spec.ports_num_for_sparse == 1
    assert job.spec.image == DEFAULT_IMAGE
    assert job.spec.passes == 1


def test_elastic_requires_fault_tolerant():
    # reference jobparser.go:66-68
    with pytest.raises(ValidationError):
        set_defaults_and_validate(mk(1, 4, ft=False))
    set_defaults_and_validate(mk(1, 4, ft=True))  # ok


def test_bad_instances():
    with pytest.raises(ValidationError):
        set_defaults_and_validate(mk(0, 0))
    with pytest.raises(ValidationError):
        set_defaults_and_validate(mk(3, 2))


def test_topology_chip_limit_mismatch():
    job = mk(tpu="8", topology=TpuTopology.parse("2x2"))
    with pytest.raises(ValidationError):
        set_defaults_and_validate(job)


def test_empty_name_rejected():
    with pytest.raises(ValidationError):
        set_defaults_and_validate(mk(name=""))
