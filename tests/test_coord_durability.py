"""Coordinator durability: state survives a coordinator crash/restart.

The reference kept coordination state in an etcd sidecar
(reference pkg/jobparser.go:167-184), so a master pod restart did not
forget the job.  Here the native server write-through-persists its state
(queue accounting, KV — checkpoint pointers! — and the membership epoch)
to --state-file before acking, and restores it at startup; the TCP client
rides out the restart by redialing.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from edl_tpu.coord.server import spawn_server

pytestmark = pytest.mark.multihost


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _kill9(handle) -> None:
    handle.process.send_signal(signal.SIGKILL)
    handle.process.wait(timeout=10)


def test_state_survives_kill9_restart(tmp_path):
    state = str(tmp_path / "coord.state")
    srv = spawn_server(member_ttl_ms=3000, task_timeout_ms=60000,
                       state_file=state)
    try:
        c = srv.client()
        for i in range(8):
            c.add_task(f"shard-{i}".encode())
        st1, id1, p1 = c.lease("w0")
        st2, id2, p2 = c.lease("w0")
        assert c.complete(id1, "w0")
        c.kv_set("ckpt/3", b"/ckpt/gen-3")
        assert c.join("w0") == 1
        assert c.join("w1") == 2
        pre = c.stats()
        assert (pre.todo, pre.leased, pre.done) == (6, 1, 1)
    finally:
        _kill9(srv)

    srv2 = spawn_server(member_ttl_ms=3000, task_timeout_ms=60000,
                        state_file=state)
    try:
        c = srv2.client()
        s = c.stats()
        # the completed task stays done; the in-flight lease re-dispatches
        # (leased -> todo: the restarted coordinator cannot know the owner
        # lives — at-least-once, same as the lease timeout)
        assert (s.todo, s.leased, s.done, s.dropped) == (7, 0, 1, 0)
        # an acked KV write is never lost
        assert c.kv_get("ckpt/3") == b"/ckpt/gen-3"
        # epoch ordering survives even though members must re-join
        epoch, members = c.members()
        assert epoch >= 2 and members == []
        # the pre-crash leaseholder's late COMPLETE is rejected (its lease
        # did not survive), so the shard re-executes exactly once
        assert not c.complete(id2, "w0")
        # drain: every shard completes exactly once across the restart
        seen = set()
        while True:
            st, tid, payload = c.lease("w1")
            if st.name != "OK":
                break
            assert payload not in seen
            seen.add(payload)
            assert c.complete(tid, "w1")
        s = c.stats()
        assert s.done == 8 and s.todo == 0 and s.dropped == 0
    finally:
        _kill9(srv2)


def test_lease_driven_rollover_survives_kill9(tmp_path):
    """A LEASE is not a mutating command — but its side effects can be
    (multi-pass rollover recycles every done task and bumps the pass,
    coord.cc MaybeAdvancePass).  A crash between that LEASE and the next
    explicit mutation must not restore the pre-rollover snapshot, or the
    finished pass replays (the round-2 advisor's medium finding)."""
    state = str(tmp_path / "coord.state")
    srv = spawn_server(task_timeout_ms=60000, state_file=state, passes=2)
    try:
        c = srv.client()
        for i in range(2):
            c.add_task(f"shard-{i}".encode())
        for _ in range(2):
            st, tid, _ = c.lease("w0")
            assert st.name == "OK"
            assert c.complete(tid, "w0")
        # pass 0 done; this LEASE rolls the pass over AND hands out a task
        st, tid, _ = c.lease("w0")
        assert st.name == "OK"
        assert c.stats().current_pass == 1
    finally:
        _kill9(srv)  # no durable command ran after the rollover lease

    srv2 = spawn_server(task_timeout_ms=60000, state_file=state, passes=2)
    try:
        s = srv2.client().stats()
        # the rollover is durable: pass 1 with both tasks pending again
        # (the in-flight lease re-dispatches), done reset — NOT the stale
        # pre-rollover snapshot (pass 0, done=2)
        assert s.current_pass == 1
        assert (s.todo, s.leased, s.done, s.dropped) == (2, 0, 0, 0)
    finally:
        _kill9(srv2)


def test_power_loss_mid_persist_keeps_last_acked_snapshot(tmp_path):
    """Power loss in the middle of a persist (temp file written, rename
    never happens): the previous COMPLETE snapshot must survive — acked
    state is never lost and a half-written file is never loaded.  Fault
    injection: --crash-on-persist N:tmp kills the server at exactly that
    boundary (the VERDICT r2 #7 'power-loss-style test')."""
    import edl_tpu.coord.client as client_mod

    state = str(tmp_path / "coord.state")
    # persists: #1 add, #2 kv ckpt, #3 trips mid-persist
    srv = spawn_server(state_file=state, crash_on_persist="3:tmp")
    c = client_mod.CoordClient("127.0.0.1", srv.port,
                               reconnect_window_s=1.0)
    c.add_task(b"shard-0")                      # persist 1, acked
    c.kv_set("ckpt/latest", b"/ckpt/gen-7")     # persist 2, acked
    with pytest.raises((client_mod.CoordError, OSError)):
        c.kv_set("ckpt/latest", b"/ckpt/gen-8")  # persist 3: dies, no ack
    srv.process.wait(timeout=10)
    assert srv.process.returncode == 137
    assert (tmp_path / "coord.state.tmp").exists()  # the torn write

    srv2 = spawn_server(state_file=state)
    try:
        c2 = srv2.client()
        # every ACKED op survives; the unacked one is absent (it was
        # never confirmed — the client's contract is retry-or-raise)
        assert c2.kv_get("ckpt/latest") == b"/ckpt/gen-7"
        s = c2.stats()
        assert (s.todo, s.done) == (1, 0)
    finally:
        _kill9(srv2)


def test_durable_but_unacked_converges_on_retry(tmp_path):
    """Crash AFTER the rename+dir-fsync but before the response: the op
    is durable yet the client never heard OK.  The client's retransmit
    against the restarted coordinator must converge (idempotent KVSET) —
    the other side of the acked=>durable guarantee."""
    import threading

    state = str(tmp_path / "coord.state")
    port = _free_port()
    srv = spawn_server(port=port, state_file=state,
                       crash_on_persist="2:acked")
    c = srv.client()
    c.kv_set("a", b"1")  # persist 1, acked

    result: dict = {}

    def do_set():
        try:
            c.kv_set("b", b"2")  # persist 2: durable, then server dies
            result["ok"] = True
        except Exception as exc:  # pragma: no cover - would fail the test
            result["error"] = str(exc)

    t = threading.Thread(target=do_set)
    t.start()
    srv.process.wait(timeout=10)
    assert srv.process.returncode == 137
    # restart on the same port inside the client's reconnect window
    srv2 = spawn_server(port=port, state_file=state)
    try:
        t.join(timeout=30)
        assert result.get("ok"), result
        c2 = srv2.client()
        assert c2.kv_get("a") == b"1"
        assert c2.kv_get("b") == b"2"  # durable before the crash AND
        # converged through the retransmit — exactly once either way
    finally:
        _kill9(srv2)


def test_client_reconnects_across_restart(tmp_path):
    state = str(tmp_path / "coord.state")
    port = _free_port()
    srv = spawn_server(port=port, state_file=state)
    c = srv.client()
    c.kv_set("k", b"v1")
    _kill9(srv)
    srv2 = spawn_server(port=port, state_file=state)
    try:
        # same client object, same address: the call redials transparently
        assert c.kv_get("k") == b"v1"
        c.kv_set("k", b"v2")
        assert c.kv_get("k") == b"v2"
    finally:
        _kill9(srv2)


@pytest.mark.slow
@pytest.mark.timeout_s(600)
@pytest.mark.needs_multiprocess_collectives
def test_workers_survive_coordinator_restart(tmp_path):
    """The VERDICT r1 #7 'done' bar: kill/restart the coordinator mid-run;
    the workers reconnect and the job finishes with exactly-once shard
    accounting."""
    state = str(tmp_path / "coord.state")
    port = _free_port()
    srv = spawn_server(port=port, member_ttl_ms=3000, task_timeout_ms=4000,
                       state_file=state)
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        EDL_MH_EXAMPLES=str(64 * 1024),
        EDL_MH_SHARDS="256",
        EDL_MH_BATCH="32",
        EDL_MH_STEP_SLEEP="0.04",
    )
    procs = {}
    logs = {}
    for n in ("w0", "w1"):
        logs[n] = tmp_path / f"{n}.log"
        procs[n] = subprocess.Popen(
            [sys.executable, "-m", "edl_tpu.runtime.multihost_worker",
             "--coord", f"127.0.0.1:{port}", "--name", n,
             "--ckpt-dir", str(tmp_path), "--min-members", "2",
             "--settle-s", "0.3", "--heartbeat-timeout-s", "5"],
            stdout=open(logs[n], "w"), stderr=subprocess.STDOUT, env=env)
    # let the world actually train, then crash the coordinator
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if logs["w0"].exists() and "step 20 " in logs["w0"].read_text():
            break
        time.sleep(0.25)
    else:
        raise TimeoutError("workers never started training")
    _kill9(srv)
    time.sleep(1.0)  # real downtime, inside the clients' redial window
    srv2 = spawn_server(port=port, member_ttl_ms=3000, task_timeout_ms=4000,
                        state_file=state)
    try:
        rcs = {n: p.wait(timeout=300) for n, p in procs.items()}
        assert rcs == {"w0": 0, "w1": 0}
        for n in procs:
            assert "done at step" in logs[n].read_text()
        s = srv2.client().stats()
        assert s.todo == 0 and s.leased == 0 and s.dropped == 0
        assert s.done == 256
    finally:
        _kill9(srv2)
