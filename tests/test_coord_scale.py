"""Coordinator scale-out: the 10k-worker control plane (ROADMAP #2,
doc/coordinator_scale.md).

PR 7 made the coordinator survive; this suite pins what makes it FAST and
WIDE: log-structured delta replication (O(delta) wire bytes, compaction
checkpoints, cross-backend format parity), epoch-fenced follower reads
(version-gated, read-your-writes, sweep-free), connection multiplexing
(tagged frames, park verbs off the critical path), coalesced KEEPALIVE
heartbeat batches, the KVWAITNE change-wait, concurrent endpoint probing
in the client constructor, and the per-verb latency histograms both
backends expose through the strict exposition parser.
"""

from __future__ import annotations

import signal
import socket
import threading
import time

import pytest

from edl_tpu.coord import (
    CoordBehind,
    CoordClient,
    CoordFenced,
    CoordMux,
    NativeCoordService,
    PyCoordService,
    native_available,
    spawn_ha_pair,
    spawn_server,
)
from edl_tpu.observability.collector import get_counters

pytestmark = pytest.mark.multihost


def _raw(port: int, line: str, timeout: float = 3.0) -> str:
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall((line + "\n").encode())
        return s.makefile("rb").readline().decode().strip()


def _kill9(handle) -> None:
    handle.process.send_signal(signal.SIGKILL)
    handle.process.wait(timeout=10)


# ---------------------------------------------------------------------------
# Delta log: Python backend semantics
# ---------------------------------------------------------------------------

class TestPyDeltaLog:
    def _pair(self):
        pr = PyCoordService()
        sb = PyCoordService(role="standby")
        pr.add_replica(sb)
        return pr, sb

    def test_mutations_stream_as_deltas_after_first_checkpoint(self):
        pr, sb = self._pair()
        # the attach itself ships the mirror its seed checkpoint
        assert (pr.repl_checkpoints, pr.repl_deltas) == (1, 0)
        pr.kv_set("a", b"1")
        pr.kv_set("b", b"2")
        pr.join("w0", "addr-0")
        pr.add_task(b"shard")
        assert pr.repl_deltas == 4    # every mutation rides the log
        assert pr.repl_checkpoints == 1
        # the mirror is byte-faithful: promote and read everything back
        sb.promote(1)
        assert sb.kv_get("a") == b"1" and sb.kv_get("b") == b"2"
        assert sb.members()[1] == [("w0", "addr-0")]
        assert sb.stats().todo == 1

    def test_delta_bytes_are_o_delta_not_o_store(self):
        pr, sb = self._pair()
        for i in range(200):          # grow the store
            pr.kv_set(f"bulk/{i}", b"x" * 64)
        snapshot_len = len(pr.snapshot(include_members=True))
        before = pr.repl_bytes
        pr.kv_set("one-more", b"y")
        delta_len = pr.repl_bytes - before
        assert delta_len * 10 < snapshot_len, (delta_len, snapshot_len)

    def test_task_transitions_replay_including_drop(self):
        pr = PyCoordService(max_task_failures=2)
        sb = PyCoordService(role="standby", max_task_failures=2)
        pr.add_replica(sb)
        t0 = pr.add_task(b"t0")
        t1 = pr.add_task(b"t1")
        _s, tid, _ = pr.lease("w")
        pr.complete(tid, "w")
        _s, tid2, _ = pr.lease("w")
        pr.fail(tid2, "w")            # failures=1, requeued
        _s, tid3, _ = pr.lease("w")
        pr.fail(tid3, "w")            # failures=2 -> dropped
        assert {t0, t1} == {tid, tid2}
        sb.promote(1)
        st = sb.stats()
        assert (st.done, st.dropped, st.todo, st.leased) == (1, 1, 0, 0)

    def test_pass_rollover_replays(self):
        pr = PyCoordService(passes=2)
        sb = PyCoordService(role="standby", passes=2)
        pr.add_replica(sb)
        pr.add_task(b"t")
        _s, tid, _ = pr.lease("w")
        pr.complete(tid, "w")         # rollover: done recycles into pass 1
        assert pr.current_pass() == 1
        sb.promote(1)
        assert sb.current_pass() == 1
        assert sb.stats().todo == 1   # recycled task mirrored

    def test_expiry_batch_is_one_epoch_bump_on_the_mirror(self):
        clock = [0]
        pr = PyCoordService(member_ttl_ms=100, clock=lambda: clock[0])
        sb = PyCoordService(role="standby", member_ttl_ms=100,
                            clock=lambda: clock[0])
        pr.add_replica(sb)
        for i in range(3):
            pr.join(f"w{i}")
        epoch0 = pr.epoch()
        clock[0] = 1_000              # all three TTLs lapse
        pr.expire_members()           # ONE sweep, ONE epoch bump
        assert pr.epoch() == epoch0 + 1
        sb.promote(1)
        assert sb.epoch() == epoch0 + 1
        assert sb.members()[1] == []

    def test_behind_replica_gets_compaction_checkpoint(self):
        pr, sb = self._pair()
        pr.kv_set("a", b"1")
        deltas0, ckpts0 = pr.repl_deltas, pr.repl_checkpoints
        # a mirror whose position the primary no longer trusts (the
        # REPLICATE re-attach shape: acked position dropped) must get a
        # compaction checkpoint, not a delta it cannot anchor
        pr._repl_acked.pop(id(sb))
        pr.kv_set("b", b"2")
        assert pr.repl_checkpoints == ckpts0 + 1
        # and once re-anchored it rides deltas again
        pr.kv_set("c", b"3")
        assert pr.repl_deltas == deltas0 + 1
        sb.promote(1)
        assert sb.kv_get("a") == b"1" and sb.kv_get("c") == b"3"

    def test_oplog_cap_forces_checkpoint(self):
        from edl_tpu.coord import service as service_mod

        pr, sb = self._pair()
        pr.kv_set("seed", b"s")
        # detach the mirror's sync by dropping its acked position, then
        # overflow the log so the gap exceeds what the log retains
        pr._repl_acked.clear()
        old_cap = service_mod.OPLOG_CAP
        try:
            service_mod.OPLOG_CAP = 4
            # _bump trims against the module constant via the class; the
            # python twin reads OPLOG_CAP at call time
            for i in range(10):
                pr._oplog and None
                pr.kv_set(f"k{i}", b"v")
        finally:
            service_mod.OPLOG_CAP = old_cap
        # the replica position (-1 after clear) forced a checkpoint and
        # the mirror still converged
        sb.promote(1)
        assert sb.kv_get("k9") == b"v"

    def test_torn_delta_rejected_without_ratcheting(self):
        sb = PyCoordService(role="standby")
        pr = PyCoordService()
        pr.add_replica(sb)
        pr.kv_set("k", b"v")
        pos = sb.stream_version()
        torn = f"EDLDELTA1 {pos} {pos + 1}\nK 6b 7a"  # no terminator
        with pytest.raises(ValueError):
            sb.sync_from(0, pos + 1, torn)
        assert sb.stream_version() == pos
        sb.promote(1)
        assert sb.kv_get("k") == b"v"  # last good mirror intact

    def test_noncontiguous_delta_rejected_as_behind(self):
        sb = PyCoordService(role="standby")
        pr = PyCoordService()
        pr.add_replica(sb)
        pr.kv_set("k", b"v")
        pos = sb.stream_version()
        blob = f"EDLDELTA1 {pos + 5} {pos + 6}\nK 6b 7a\n.\n"
        with pytest.raises(ValueError, match="behind"):
            sb.sync_from(0, pos + 6, blob)
        assert sb.stream_version() == pos


# ---------------------------------------------------------------------------
# Delta log: cross-backend format parity
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not native_available(), reason="no native core")
class TestDeltaFormatParity:
    def test_python_delta_applies_into_native(self):
        py = PyCoordService()
        mirror = PyCoordService(role="standby")
        py.add_replica(mirror)
        py.kv_set("seed", b"s")       # checkpoint boundary
        native = NativeCoordService()
        assert native.restore_repl(py.snapshot(include_members=True))
        base = native.stream_version()
        assert base == py.stream_version()
        py.join("w0")                 # empty address: "-" framing
        py.kv_set("flag", b"")        # empty value: "-" framing
        py.add_task(b"")              # empty payload: "-" framing
        py.kv_del("seed")
        blob = py._delta_blob(base, py.stream_version())
        assert blob is not None and blob.startswith("EDLDELTA1 ")
        assert native.apply_delta(blob) == py.stream_version()
        assert native.members()[1] == [("w0", "")]
        assert native.kv_get("flag") == b""
        assert native.kv_get("seed") is None
        st, _tid, payload = native.lease("w")
        assert st.name == "OK" and payload == b""

    def test_native_server_delta_applies_into_python(self, tmp_path):
        """Capture a REAL delta off the native server's replication
        stream (a fake standby socket plays the mirror) and restore it
        through PyCoordService.sync_from — the wire format is one
        format, both backends, including the checkpoint boundary."""
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(4)
        sb_port = listener.getsockname()[1]
        pr = spawn_server(state_file=str(tmp_path / "a.state"),
                          replicate_to=f"127.0.0.1:{sb_port}",
                          repl_lease_ms=60_000)
        py_mirror = PyCoordService(role="standby")
        stop = threading.Event()

        def fake_standby() -> None:
            listener.settimeout(10)
            while not stop.is_set():
                try:
                    conn, _ = listener.accept()
                except (socket.timeout, OSError):
                    return
                conn.settimeout(10)
                rfile = conn.makefile("rb")
                while not stop.is_set():
                    try:
                        line = rfile.readline()
                    except OSError:
                        break
                    if not line:
                        break
                    tokens = line.decode().strip().split(" ")
                    if tokens[0] != "SYNC":
                        conn.sendall(b"OK\n")
                        continue
                    fence, ver = int(tokens[1]), int(tokens[2])
                    blob = bytes.fromhex(tokens[3]).decode()
                    try:
                        pos = py_mirror.sync_from(fence, ver, blob)
                        kinds.append(blob.split(" ")[0].split("\n")[0])
                        conn.sendall(f"OK {pos}\n".encode())
                    except ValueError:
                        conn.sendall(b"ERR behind\n")
                conn.close()

        kinds: list[str] = []
        t = threading.Thread(target=fake_standby, daemon=True)
        t.start()
        try:
            c = CoordClient("127.0.0.1", pr.port, timeout=3.0,
                            reconnect_window_s=8.0)
            c.kv_set("k1", b"v1")     # first stream: EDLCOORD1 checkpoint
            c.join("w0", "a0")        # then EDLDELTA1 records
            c.kv_set("k2", b"v2")
            deadline = time.monotonic() + 10
            while len(kinds) < 3 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert kinds[0] == "EDLCOORD1"
            assert set(kinds[1:]) == {"EDLDELTA1"}, kinds
            # the python mirror is faithful at the native position
            assert py_mirror.stream_version() == \
                int(_raw(pr.port, "ROLE").split(" ")[3])
            py_mirror.promote(1)
            assert py_mirror.kv_get("k2") == b"v2"
            assert py_mirror.members()[1] == [("w0", "a0")]
            c.close()
        finally:
            stop.set()
            listener.close()
            pr.stop()

    def test_native_server_rejects_torn_delta_without_ratchet(
            self, tmp_path):
        sb = spawn_server(standby=True,
                          state_file=str(tmp_path / "sb.state"))
        try:
            # seed the mirror with a checkpoint at position 1
            py = PyCoordService()
            py.kv_set("k", b"v")
            ck = py.snapshot(include_members=True)
            assert _raw(sb.port, f"SYNC 0 1 {ck.encode().hex()}"
                        ).startswith("OK")
            pos = int(_raw(sb.port, "ROLE").split(" ")[3])
            torn = f"EDLDELTA1 {pos} {pos + 1}\nK 6b 7a".encode().hex()
            assert _raw(sb.port, f"SYNC 0 {pos + 1} {torn}") \
                == "ERR badblob"
            assert int(_raw(sb.port, "ROLE").split(" ")[3]) == pos
            # a non-contiguous (but well-framed) delta is "behind"
            ahead = (f"EDLDELTA1 {pos + 7} {pos + 8}\nK 6b 7a\n.\n"
                     .encode().hex())
            assert _raw(sb.port, f"SYNC 0 {pos + 8} {ahead}") \
                == "ERR behind"
            assert int(_raw(sb.port, "ROLE").split(" ")[3]) == pos
        finally:
            sb.stop()

    def test_native_pair_converges_through_delta_then_kill(
            self, tmp_path):
        """End-to-end on the native pair: mutations ride deltas (counted
        on METRICS), the promoted standby owns them all after a kill —
        the PR 7 guarantee on the delta path."""
        pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000)
        c = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                        reconnect_window_s=12.0, promote_grace_s=0.2,
                        endpoints=[("127.0.0.1", sb.port)])
        try:
            for i in range(20):
                c.kv_set(f"k{i}", b"v%d" % i)
            m = c.server_metrics()
            assert m["repl_deltas"] >= 19, m
            assert m["repl_checkpoints"] >= 1
            assert m["repl_bytes"] * 1 < m["snapshot_bytes"] * 20, m
            _kill9(pr)
            for i in range(20):
                assert c.kv_get(f"k{i}") == b"v%d" % i
            assert (c.host, c.port) == ("127.0.0.1", sb.port)
        finally:
            c.close()
            pr.stop()
            sb.stop()


# ---------------------------------------------------------------------------
# Follower reads
# ---------------------------------------------------------------------------

class TestFollowerReadsPy:
    def test_version_gated_read_your_writes(self):
        pr = PyCoordService()
        sb = PyCoordService(role="standby")
        pr.add_replica(sb)
        pr.kv_set("k", b"v")
        floor = pr.stream_version()
        with sb.follower_read(0, floor):
            assert sb.kv_get("k") == b"v"
            assert sb.kv_keys() == ["k"]
        assert sb.follower_reads == 1
        # outside the admission, the standby still fences everything
        with pytest.raises(CoordFenced):
            sb.kv_get("k")

    def test_behind_mirror_parks_then_raises(self):
        sb = PyCoordService(role="standby")
        t0 = time.monotonic()
        with pytest.raises(CoordBehind):
            with sb.follower_read(0, 100, timeout_s=0.3):
                pass
        assert 0.25 <= time.monotonic() - t0 < 2.0

    def test_catchup_wakes_parked_admission(self):
        pr = PyCoordService()
        sb = PyCoordService(role="standby")
        out = []

        def reader() -> None:
            with sb.follower_read(0, 1, timeout_s=5.0):
                out.append(sb.kv_get("k"))

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.1)
        pr.add_replica(sb)
        pr.kv_set("k", b"v")          # stream catches the mirror up
        t.join(timeout=5)
        assert out == [b"v"]

    def test_stale_fence_rejected(self):
        sb = PyCoordService(role="standby")
        with pytest.raises(CoordFenced):
            with sb.follower_read(3, 0):
                pass

    def test_follower_read_never_sweeps(self):
        clock = [0]
        pr = PyCoordService(member_ttl_ms=100, clock=lambda: clock[0])
        sb = PyCoordService(role="standby", member_ttl_ms=100,
                            clock=lambda: clock[0])
        pr.add_replica(sb)
        pr.join("w0")
        clock[0] = 10_000             # TTL long gone
        with sb.follower_read(0, 0):
            # the mirror sees no heartbeats; a sweep here would
            # fabricate an epoch bump the primary never made
            assert sb.members()[1] == [("w0", "")]
            assert sb.epoch() == 1
        # and the primary, which DOES sweep, still owns TTL truth
        assert pr.members()[1] == []


class TestFollowerReadsNative:
    def test_read_verbs_on_standby_with_version_gate(self, tmp_path):
        pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000)
        try:
            assert _raw(pr.port, "KVSET k " + b"v".hex()).startswith("OK")
            assert _raw(pr.port, "JOIN w0 a0").startswith("OK")
            sv = int(_raw(pr.port, "ROLE").split(" ")[3])
            # served at the floor the client's writes acked
            assert _raw(sb.port, f"READ 0 {sv} KVGET k") \
                == "OK " + b"v".hex()
            assert _raw(sb.port, f"READ 0 {sv} MEMBERS") == "OK 1 w0=a0"
            assert _raw(sb.port, f"READ 0 {sv} STATS").startswith("OK")
            # an impossible floor redirects instead of serving stale
            assert _raw(sb.port, f"READ 0 {sv + 50} KVGET k",
                        timeout=6.0).startswith("ERR behind")
            # a mutation through the READ gate is refused
            assert _raw(sb.port, f"READ 0 0 KVSET k {b'x'.hex()}") \
                == "ERR readonly"
            # a fencing regime this mirror has not seen is refused
            assert _raw(sb.port, "READ 9 0 KVGET k").startswith(
                "ERR stale")
            # bare (non-READ) verbs stay fenced — PR 7 semantics intact
            assert _raw(sb.port, "KVGET k").startswith("ERR fenced")
        finally:
            pr.stop()
            sb.stop()

    def test_client_routes_reads_to_follower(self, tmp_path):
        pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000)
        c = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                        reconnect_window_s=10.0,
                        endpoints=[("127.0.0.1", sb.port)],
                        follower_reads=True)
        try:
            c.kv_set("k", b"v")       # ack carries the version floor
            assert c._min_version >= 1
            before = int(_raw(sb.port, "METRICS").split(" ")[8])
            assert c.kv_get("k") == b"v"          # read-your-write
            _epoch, members = c.members()
            assert members == []
            after = int(_raw(sb.port, "METRICS").split(" ")[8])
            assert after >= before + 2            # standby served them
            # primary-frozen probe: the follower keeps serving reads
            pr.process.send_signal(signal.SIGSTOP)
            try:
                assert c.kv_get("k") == b"v"
            finally:
                pr.process.send_signal(signal.SIGCONT)
        finally:
            c.close()
            pr.stop()
            sb.stop()

    def test_follower_longpoll_fires_on_replicated_change(self, tmp_path):
        pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000)
        c = CoordClient("127.0.0.1", pr.port, timeout=3.0,
                        reconnect_window_s=10.0,
                        endpoints=[("127.0.0.1", sb.port)],
                        follower_reads=True)
        cw = CoordClient("127.0.0.1", pr.port, timeout=3.0,
                         reconnect_window_s=10.0)
        fired = []
        try:
            t = threading.Thread(
                target=lambda: fired.append(c.kv_wait("key", 10.0)))
            t.start()
            time.sleep(0.3)           # parked (on the follower)
            cw.kv_set("key", b"val")  # lands on the primary, streams over
            t.join(timeout=10)
            assert fired == [(b"val", None)]
        finally:
            c.close()
            cw.close()
            pr.stop()
            sb.stop()


# ---------------------------------------------------------------------------
# Multiplexing + batching + change-wait
# ---------------------------------------------------------------------------

class TestMux:
    def test_interleaved_slots_one_socket(self, tmp_path):
        srv = spawn_server()
        mux = CoordMux("127.0.0.1", srv.port, timeout=3.0)
        try:
            clients = [mux.client() for _ in range(16)]
            for i, c in enumerate(clients):
                assert c.join(f"m{i}", f"a{i}") == i + 1
            # one slot parks; its siblings' requests keep flowing on the
            # SAME connection (the tagged park runs off-thread)
            fired = []
            t = threading.Thread(target=lambda: fired.append(
                clients[0].wait_epoch(16, 10.0)))
            t.start()
            time.sleep(0.2)
            t0 = time.monotonic()
            for _ in range(30):
                assert clients[5].kv_get("nope") is None
            assert time.monotonic() - t0 < 1.0
            clients[7].join("late", "x")
            t.join(timeout=5)
            assert fired == [17]
        finally:
            mux.close()
            srv.stop()

    def test_mux_failover_promotes(self, tmp_path):
        pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000)
        mux = CoordMux("127.0.0.1", pr.port, timeout=2.0,
                       reconnect_window_s=15.0, promote_grace_s=0.2,
                       endpoints=[("127.0.0.1", sb.port)])
        try:
            c = mux.client()
            c.kv_set("k", b"v")
            _kill9(pr)
            assert c.kv_get("k") == b"v"
            assert mux.port == sb.port
            assert _raw(sb.port, "ROLE").startswith("OK primary")
        finally:
            mux.close()
            pr.stop()
            sb.stop()

    def test_mux_client_pickles_to_standalone(self, tmp_path):
        import pickle

        srv = spawn_server()
        mux = CoordMux("127.0.0.1", srv.port, timeout=3.0)
        try:
            c = mux.client()
            c.kv_set("k", b"v")
            c2 = pickle.loads(pickle.dumps(c))
            assert type(c2) is CoordClient   # plain, own socket
            assert c2.kv_get("k") == b"v"
            c2.close()
        finally:
            mux.close()
            srv.stop()

    def test_keepalive_batch_and_expiry_report(self, tmp_path):
        srv = spawn_server(member_ttl_ms=600)
        c = srv.client()
        try:
            for i in range(5):
                c.join(f"m{i}")
            hb = c.heartbeat_many([f"m{i}" for i in range(5)] + ["ghost"])
            assert sum(hb.values()) == 5 and hb["ghost"] is False
            # one wire request for the whole batch
            before = c.server_metrics()["requests_served"]
            c.heartbeat_many([f"m{i}" for i in range(5)])
            after = c.server_metrics()["requests_served"]
            assert after - before == 2  # KEEPALIVE + the METRICS itself
        finally:
            c.close()
            srv.stop()

    def test_batch_keepalive_rejoins_expired(self, tmp_path):
        from edl_tpu.runtime.discovery import BatchKeepalive

        srv = spawn_server(member_ttl_ms=400)
        c = srv.client()
        try:
            ka = BatchKeepalive(c, interval_s=0.1)
            for i in range(4):
                c.join(f"m{i}", f"a{i}")
                ka.add(f"m{i}", f"a{i}")
            assert ka.beat_once() == 4
            time.sleep(0.6)           # everyone expires (no beats)
            c.expire = None           # (no-op; readability)
            assert c.members()[1] == []
            ka.beat_once()            # batch reports expiry -> rejoins
            assert len(c.members()[1]) == 4
            # an evicted name stays out
            c.kv_set("evict/m0", b"1")
            time.sleep(0.6)
            assert c.members()[1] == []
            ka.beat_once()
            assert [n for n, _ in c.members()[1]] == ["m1", "m2", "m3"]
        finally:
            c.close()
            srv.stop()

    def test_mux_degrades_against_pre_scaleout_server(self):
        """A pre-scale-out server parses '#<id>' as the command and
        answers an UNTAGGED 'ERR unknown': the connect-time tagged PING
        probe must detect that and degrade the mux to one-request-at-a-
        time pipelining — mixed-fleet rolling upgrades must work, just
        serialized."""
        svc = PyCoordService()
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(4)
        stop = threading.Event()

        def old_server() -> None:
            lst.settimeout(5)
            while not stop.is_set():
                try:
                    conn, _ = lst.accept()
                except (socket.timeout, OSError):
                    return
                rfile = conn.makefile("rb")
                while not stop.is_set():
                    line = rfile.readline()
                    if not line:
                        break
                    p = line.decode().strip().split(" ")
                    if p[0] == "PING":
                        resp = "PONG"
                    elif p[0] == "KVSET":
                        svc.kv_set(p[1], bytes.fromhex(p[2])
                                   if p[2] != "-" else b"")
                        resp = "OK"
                    elif p[0] == "KVGET":
                        v = svc.kv_get(p[1])
                        resp = "NONE" if v is None else "OK " + v.hex()
                    elif p[0] == "HB":
                        resp = ("OK" if svc.heartbeat(p[1])
                                else "ERR rejoin")
                    elif p[0] == "JOIN":
                        resp = f"OK {svc.join(p[1])}"
                    else:
                        resp = "ERR unknown"  # tags land here
                    conn.sendall((resp + "\n").encode())
                conn.close()

        t = threading.Thread(target=old_server, daemon=True)
        t.start()
        mux = CoordMux("127.0.0.1", lst.getsockname()[1], timeout=2.0,
                       reconnect_window_s=5.0)
        try:
            assert mux._tagged is False
            c1, c2 = mux.client(), mux.client()
            c1.kv_set("k", b"v")
            assert c2.kv_get("k") == b"v"
            assert c1.join("w0") == 1
            # batch heartbeats degrade to individual HBs transparently
            assert c1.heartbeat_many(["w0", "ghost"]) \
                == {"w0": True, "ghost": False}
        finally:
            mux.close()
            stop.set()
            lst.close()

    def test_kv_wait_changed_fires_on_change_and_delete(self, tmp_path):
        srv = spawn_server()
        c = srv.client()
        cw = srv.client()
        try:
            c.kv_set("g", b"1")
            out = []
            t = threading.Thread(target=lambda: out.append(
                c.kv_wait_changed("g", b"1", 10.0)))
            t.start()
            time.sleep(0.2)
            cw.kv_set("g", b"2")
            t.join(timeout=5)
            assert out == [(True, b"2")]
            # delete fires too
            t = threading.Thread(target=lambda: out.append(
                c.kv_wait_changed("g", b"2", 10.0)))
            t.start()
            time.sleep(0.2)
            cw.kv_del("g")
            t.join(timeout=5)
            assert out[-1] == (True, None)
            # absent -> appearance fires
            t = threading.Thread(target=lambda: out.append(
                c.kv_wait_changed("g", None, 10.0)))
            t.start()
            time.sleep(0.2)
            cw.kv_set("g", b"3")
            t.join(timeout=5)
            assert out[-1] == (True, b"3")
            # timeout
            assert c.kv_wait_changed("g", b"3", 0.2) == (False, None)
        finally:
            c.close()
            cw.close()
            srv.stop()


# ---------------------------------------------------------------------------
# Constructor: concurrent endpoint probing
# ---------------------------------------------------------------------------

def _blackhole() -> tuple[socket.socket, int, list]:
    """A listener whose SYN backlog is saturated: connects HANG (no
    accept, no RST) — the worst-case endpoint shape for a serial dial."""
    bh = socket.socket()
    bh.bind(("127.0.0.1", 0))
    bh.listen(0)
    fillers = []
    for _ in range(4):
        s = socket.socket()
        s.setblocking(False)
        try:
            s.connect(("127.0.0.1", bh.getsockname()[1]))
        except BlockingIOError:
            pass
        fillers.append(s)
    time.sleep(0.1)
    return bh, bh.getsockname()[1], fillers


def test_constructor_short_circuits_past_blackholed_endpoint():
    bh, bh_port, fillers = _blackhole()
    srv = spawn_server()
    try:
        t0 = time.monotonic()
        c = CoordClient("127.0.0.1", bh_port, timeout=5.0,
                        reconnect_window_s=20.0,
                        endpoints=[("127.0.0.1", srv.port)])
        dt = time.monotonic() - t0
        # serial dialing would burn ~timeout on the black hole FIRST;
        # concurrent probing connects to the live primary immediately
        assert dt < 4.0, dt
        assert (c.host, c.port) == ("127.0.0.1", srv.port)
        c.kv_set("k", b"v")
        assert c.kv_get("k") == b"v"
        c.close()
    finally:
        for s in fillers:
            s.close()
        bh.close()
        srv.stop()


def test_constructor_prefers_primary_over_standby_listed_first(tmp_path):
    pr, sb = spawn_ha_pair(str(tmp_path))
    try:
        # the standby is listed FIRST; the concurrent ROLE probe must
        # still land the client on the primary
        c = CoordClient("127.0.0.1", sb.port, timeout=3.0,
                        reconnect_window_s=10.0,
                        endpoints=[("127.0.0.1", pr.port)])
        assert (c.host, c.port) == ("127.0.0.1", pr.port)
        c.kv_set("k", b"v")           # no fenced-redirect needed
        c.close()
    finally:
        pr.stop()
        sb.stop()


# ---------------------------------------------------------------------------
# Per-verb latency histograms (both backends, strict parser)
# ---------------------------------------------------------------------------

def test_native_verb_histograms_strict_exposition(tmp_path):
    import urllib.request

    from edl_tpu.observability.metrics import parse_exposition

    srv = spawn_server(health_port=0)
    c = srv.client()
    try:
        c.kv_set("k", b"v")
        c.kv_get("k")
        c.join("w0", "a0")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.health_port}/metrics",
                timeout=5) as r:
            body = r.read().decode()
        # the strict parser IS the assertion: a histogram-contract or
        # grammar violation raises
        series = parse_exposition(body)
        for verb in ("KVSET", "KVGET", "JOIN"):
            assert series[
                f'edl_coord_verb_seconds_count{{verb="{verb}"}}'] >= 1
            assert series[
                f'edl_coord_verb_seconds_bucket{{verb="{verb}",'
                f'le="+Inf"}}'] >= 1
        # replication accounting renders too
        assert "edl_coord_repl_bytes_total" in series
        assert "edl_coord_repl_deltas_total" in series
        assert "edl_coord_follower_reads_total" in series
    finally:
        c.close()
        srv.stop()


def test_py_service_verb_histograms_strict_exposition():
    from edl_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry()
    svc = PyCoordService()
    svc.register_metrics(reg)
    svc.kv_set("k", b"v")
    svc.kv_get("k")
    svc.join("w0", "a0")
    from edl_tpu.observability.metrics import parse_exposition

    series = parse_exposition(reg.render())
    for verb in ("KVSET", "KVGET", "JOIN"):
        assert series[
            f'edl_coord_verb_seconds_count{{verb="{verb}"}}'] >= 1
    assert "edl_coord_repl_bytes_total" in series
    assert "edl_coord_repl_deltas_total" in series


# ---------------------------------------------------------------------------
# Serving weight watcher: KV long-poll instead of fixed-interval polling
# ---------------------------------------------------------------------------

def test_weight_watcher_longpolls_generation_key():
    """The watcher parks on KVWAITNE against serving-gen/<job>; a
    published generation wakes the reload within one cycle, and with a
    scan backstop the skipped filesystem scans are counted."""
    from edl_tpu.runtime import serving as serving_mod

    class FakeFleet:
        job = "ns/job"
        generation = 1

        def __init__(self, kv) -> None:
            self._kv = kv
            self.reloads = 0

        def reload_from_lineage(self, _ck) -> None:
            self.reloads += 1

    srv = spawn_server()
    kv = srv.client()
    try:
        saved0 = get_counters().total("serving_lineage_polls_saved")
        fleet = FakeFleet(kv)
        w = serving_mod._WeightWatcher(fleet, checkpointer=None,
                                       poll_s=0.3, scan_backstop=50)
        w.start()
        time.sleep(1.0)               # several timed-out parks: scans
        assert fleet.reloads <= 1     # gated by the backstop
        assert get_counters().total(
            "serving_lineage_polls_saved") > saved0
        reloads0 = fleet.reloads
        kv.kv_set("serving-gen/ns/job", b"7")   # published generation
        deadline = time.monotonic() + 5
        while fleet.reloads == reloads0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fleet.reloads > reloads0          # woke within one cycle
        w.stop()
        # fallback: no KV wired -> plain sleep-poll still reloads, and
        # the scan backstop is IGNORED (nothing watches the key, so a
        # skipped scan would just be a reload-latency multiplier)
        fleet2 = FakeFleet(None)
        w2 = serving_mod._WeightWatcher(fleet2, checkpointer=None,
                                        poll_s=0.1, scan_backstop=50)
        w2.start()
        time.sleep(0.5)
        w2.stop()
        assert fleet2.reloads >= 2
    finally:
        kv.close()
        srv.stop()
