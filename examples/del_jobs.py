"""Bulk job deleter — role of the reference's example/del_jobs.sh
(delete every TrainingJob and its worker groups).

    python examples/del_jobs.py [--namespace default] [--kubeconfig ...]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path + platform pin)

import argparse

from edl_tpu.api.types import TrainingJob


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--kubeconfig", default=None)
    args = ap.parse_args()

    from edl_tpu.cluster.k8s import K8sCluster

    cluster = K8sCluster(kubeconfig=args.kubeconfig, namespace=args.namespace)
    names = cluster.list_training_jobs()
    for name in names:
        cluster.delete_resources(TrainingJob(name=name,
                                             namespace=args.namespace))
        print(f"deleted {args.namespace}/{name}")
    if not names:
        print("no TrainingJobs found")


if __name__ == "__main__":
    main()
