"""Bulk job deleter — role of the reference's example/del_jobs.sh
(delete every TrainingJob and its worker groups).

    python examples/del_jobs.py [--namespace default] [--kubeconfig ...]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path + platform pin)

import argparse

from edl_tpu.api.types import TrainingJob


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--kubeconfig", default=None)
    args = ap.parse_args()

    from edl_tpu.cluster.k8s import K8sCluster

    cluster = K8sCluster(kubeconfig=args.kubeconfig, namespace=args.namespace)
    # CRs first (the controller tears down what it manages), then any
    # group left behind (controller down / never-managed jobs)
    names = set(cluster.list_training_jobs())
    for cr in cluster.list_training_job_crs():
        meta = cr.get("metadata") or {}
        if meta.get("namespace", "default") == args.namespace:
            cluster.delete_training_job_cr(meta.get("name", ""))
            names.add(meta.get("name", ""))
    for name in sorted(names):
        cluster.delete_resources(TrainingJob(name=name,
                                             namespace=args.namespace))
        print(f"deleted {args.namespace}/{name}")
    if not names:
        print("no TrainingJobs found")


if __name__ == "__main__":
    main()
