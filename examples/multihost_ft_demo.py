"""Multi-host fault-tolerance, live: kill a worker AND the coordinator.

Runs the full elastic multi-host stack on this machine with CPU jax
processes (the same code path a TPU pod would run):

1. a DURABLE coordination server (``--state-file``: queue accounting,
   checkpoint pointers and the membership epoch survive restarts);
2. three elastic workers training one job from the shared task queue;
3. ~5 s in: ``kill -9`` one worker — the survivors reform a 2-world and
   its leased shards re-dispatch (reference: a dead trainer is a
   non-event, docker/paddle_k8s:119-141 + the 16 s re-dispatch);
4. ~10 s in: ``kill -9`` the coordinator, then restart it on the same
   port — workers redial, membership rebuilds from heartbeats, training
   continues (reference: the etcd sidecar's persistence,
   pkg/jobparser.go:167-184);
5. both survivors drain the queue and exit 0 with exactly-once shard
   accounting.

Usage:  python examples/multihost_ft_demo.py [--model transformer]

``--model transformer`` runs the real GQA decoder family (the bench's
architecture) through the same fault story, with mid-world checkpoints
bounding the crash loss to 20 steps.
"""

import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from edl_tpu.coord.server import spawn_server  # noqa: E402


def wait_for(path: str, needle: str, timeout_s: float) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(path) and needle in open(path).read():
            return
        time.sleep(0.25)
    raise TimeoutError(f"{needle!r} never appeared in {path}")


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=("mlp", "transformer"),
                    default="mlp",
                    help="transformer = the GQA decoder family (the "
                         "bench's architecture) through the fault story")
    model = ap.parse_args().model
    work = tempfile.mkdtemp(prefix="edl-mh-demo-")
    state = os.path.join(work, "coord.state")
    n_shards = 256 if model == "mlp" else 64
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        EDL_MH_EXAMPLES=str(64 * 1024), EDL_MH_SHARDS=str(n_shards),
        EDL_MH_BATCH="32", EDL_MH_STEP_SLEEP="0.04",
        # CPU demo: disarm the axon TPU bootstrap hook (~5 s of jax
        # import per interpreter start) and reap the tree if the demo dies
        PALLAS_AXON_POOL_IPS="",
        EDL_MH_DIE_WITH_PARENT="1",
    )
    if model == "transformer":
        env.update(EDL_MH_SEQ="32", EDL_MH_BATCH="16",
                   EDL_MH_CKPT_EVERY="20", EDL_MH_EXAMPLES=str(16 * 1024))

    print(f"== durable coordinator (state write-through: {state})")
    srv = spawn_server(member_ttl_ms=3000, task_timeout_ms=4000,
                       state_file=state)
    port = srv.port

    print("== 3 elastic workers join, one world forms")
    procs, logs = {}, {}
    for n in ("w0", "w1", "w2"):
        logs[n] = os.path.join(work, f"{n}.log")
        procs[n] = subprocess.Popen(
            [sys.executable, "-m", "edl_tpu.runtime.multihost_worker",
             "--coord", f"127.0.0.1:{port}", "--name", n,
             "--ckpt-dir", work, "--min-members", "3",
             "--model", model,
             "--settle-s", "0.3", "--heartbeat-timeout-s", "5"],
            stdout=open(logs[n], "w"), stderr=subprocess.STDOUT, env=env)
    wait_for(logs["w0"], "step 20 ", 180)
    print("   training underway (w0 passed step 20)")

    print("== kill -9 w1: a dead trainer is a non-event")
    procs["w1"].kill()
    procs["w1"].wait()
    wait_for(logs["w0"], "world=2", 120)
    print("   survivors reformed a 2-world; w1's leased shards re-dispatch")

    print("== kill -9 the coordinator, restart it on the same port")
    srv.process.send_signal(signal.SIGKILL)
    srv.process.wait()
    time.sleep(1.0)
    srv = spawn_server(port=port, member_ttl_ms=3000, task_timeout_ms=4000,
                       state_file=state)
    print("   restarted; workers redial, membership rebuilds from heartbeats")

    rc0 = procs["w0"].wait(timeout=300)
    rc2 = procs["w2"].wait(timeout=300)
    stats = srv.client().stats()
    srv.stop()
    print(f"== done: w0 rc={rc0}, w2 rc={rc2}")
    print(f"   queue: done={stats.done} todo={stats.todo} "
          f"leased={stats.leased} dropped={stats.dropped}")
    ok = (rc0 == 0 and rc2 == 0 and stats.done == n_shards
          and stats.todo == 0 and stats.dropped == 0)
    print("   exactly-once accounting:", "OK" if ok else "VIOLATED")
    for n in ("w0", "w2"):
        line = [l for l in open(logs[n]).read().splitlines()
                if "done at step" in l]
        if line:
            print(f"   {line[-1]}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
