"""Multi-host fault-tolerance, live, through the DEPLOYED path.

A thin wrapper over the same stack the e2e test drives
(tests/test_exec_kubelet_e2e.py): a Controller materializes the job on a
FakeCluster whose pods a ProcessKubelet actually EXECS — the coordinator
pod runs `python -m edl_tpu.coord.server`, each trainer pod runs
`python -m edl_tpu.runtime.launcher start_trainer`, exactly the commands
the shipped manifests declare (controller/jobparser.py; reference
parity: pkg/jobparser.go:124 + docker/paddle_k8s:119-141).  Then the
fault story:

1. three trainer pods form a world and train from the shared task queue;
2. kill -9 one trainer's process group — the survivors reform and the
   Job controller replaces the pod (a dead trainer is a non-event,
   reference docker/paddle_k8s:119-141 + the 16 s re-dispatch);
3. kill -9 the coordinator pod's process — the ReplicaSet analogue
   respawns it on the same state volume (PVC semantics), workers redial,
   membership rebuilds from heartbeats (reference: the etcd sidecar's
   persistence, pkg/jobparser.go:167-184);
4. the queue drains with exactly-once accounting and the job Succeeds.

Usage:  python examples/multihost_ft_demo.py [--model transformer]
"""

import _bootstrap  # noqa: F401  (repo-root sys.path + platform pin)

import os
import re
import signal
import sys
import tempfile
import time

from edl_tpu.api.serde import job_from_dict
from edl_tpu.api.types import JobPhase
from edl_tpu.cluster.exec_kubelet import ProcessKubelet
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.controller.controller import Controller
from edl_tpu.coord.client import CoordClient


def wait_for(cond, what: str, timeout_s: float):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.25)
    raise TimeoutError(f"never reached: {what}")


def main() -> int:
    import argparse
    import glob
    import socket

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=("mlp", "transformer"),
                    default="mlp",
                    help="transformer = the GQA decoder family (the "
                         "bench's architecture) through the fault story")
    model = ap.parse_args().model
    work = tempfile.mkdtemp(prefix="edl-mh-demo-")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    n_shards = 256 if model == "mlp" else 64
    overrides = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PALLAS_AXON_POOL_IPS": "",
        "EDL_MH_DIE_WITH_PARENT": "1",
        "EDL_MH_EXAMPLES": str(64 * 1024), "EDL_MH_SHARDS": str(n_shards),
        "EDL_MH_BATCH": "32", "EDL_MH_STEP_SLEEP": "0.04",
        "EDL_MH_MODEL": model,
        "EDL_HEALTH_PORT": "0",
        "EDL_COORD_MEMBER_TTL_MS": "3000",
        "EDL_COORD_TASK_TIMEOUT_MS": "4000",
        "EDL_MH_WARM_SPAWN": "0",
    }
    if model == "transformer":
        overrides.update(EDL_MH_SEQ="32", EDL_MH_BATCH="16",
                         EDL_MH_CKPT_EVERY="20",
                         EDL_MH_EXAMPLES=str(16 * 1024))

    print("== control plane: FakeCluster + process-backed kubelet "
          "(pods exec the SHIPPED commands)")
    fake = FakeCluster()
    fake.add_node("host0", cpu_milli=16000, memory_mega=16000, tpu_chips=8)
    controller = Controller(fake, updater_convert_seconds=0.3,
                            updater_confirm_seconds=0.2)
    kubelet = ProcessKubelet(fake, work, env_overrides=overrides)

    entry = (
        "python -m edl_tpu.runtime.multihost_worker"
        " --coord $EDL_COORD_HOST:$EDL_COORD_PORT"
        " --name $EDL_WORKER_NAME"
        f" --ckpt-dir {work}/ckpt"
        " --min-members 3 --settle-s 0.3 --heartbeat-timeout-s 5"
        f" --model {model}"
    )
    job = job_from_dict({
        "apiVersion": "edl.tpu/v1", "kind": "TrainingJob",
        "metadata": {"name": "demo"},
        "spec": {
            "image": "edl-tpu-job:latest", "fault_tolerant": True,
            "port": port,
            "trainer": {
                "entrypoint": entry, "min_instance": 3, "max_instance": 3,
                "resources": {"requests": {"cpu": "500m",
                                           "memory": "256Mi"},
                              "limits": {"cpu": "1", "memory": "512Mi",
                                         "google.com/tpu": "1"}},
            },
        },
    })

    def tlogs():
        return sorted(glob.glob(os.path.join(work, "logs",
                                             "demo-trainer-*.log")))

    def text():
        return "".join(open(p).read() for p in tlogs())

    def worlds():
        return [int(m.group(1)) for m in
                re.finditer(r"entering world epoch=\d+ world=(\d+)",
                            text())]

    stats = None

    def poll_stats():
        # keep the HIGHEST done-count seen: on success the updater tears
        # the coordinator down at once, and a last poll that raced the
        # teardown must not roll the evidence back to an earlier snapshot
        nonlocal stats
        try:
            c = CoordClient("127.0.0.1", port, timeout=2.0)
            s = c.stats()
            c.close()
            if stats is None or s.done >= stats.done:
                stats = s
        except OSError:
            pass

    try:
        controller.submit(job)
        print("== 3 trainer pods exec `launcher start_trainer`; "
              "one world forms")
        wait_for(lambda: any(w == 3 for w in worlds()),
                 "3-world forms", 180)
        wait_for(lambda: "step 20 " in text(), "training underway", 120)
        print("   training underway (step 20 logged)")

        print("== kill -9 one trainer pod: a dead trainer is a non-event")
        victim = [p for p in kubelet.live_pods() if "-trainer-" in p][0]
        before = set(tlogs())
        kubelet.signal_pod(victim, signal.SIGKILL)
        wait_for(lambda: any("entering world" in open(p).read()
                             for p in set(tlogs()) - before),
                 "replacement pod rejoins", 180)
        print(f"   {victim} killed; survivors reformed; replacement "
              "pod rejoined")

        print("== kill -9 the coordinator pod: the RS respawns it on the "
              "same state volume")
        coord_pod = [p for p in kubelet.live_pods()
                     if "-coordinator-" in p][0]
        kubelet.signal_pod(coord_pod, signal.SIGKILL)
        wait_for(lambda: any(p != coord_pod and "-coordinator-" in p
                             for p in kubelet.live_pods()),
                 "coordinator replaced", 60)
        print("   restarted; workers redial, membership rebuilds, "
              "queue state restored from the volume")

        print("== drain to completion")
        updater = controller.get_updater(job)
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            poll_stats()
            if updater.job.status.phase in (JobPhase.SUCCEEDED,
                                            JobPhase.FAILED):
                break
            time.sleep(0.3)
        phase = updater.job.status.phase
        ok = (phase == JobPhase.SUCCEEDED and stats is not None
              and stats.done == n_shards and stats.todo == 0
              and stats.dropped == 0)
        print(f"== done: phase={phase.value} queue="
              f"{stats and (stats.done, stats.todo, stats.dropped)}")
        print("   exactly-once accounting:", "OK" if ok else "VIOLATED")
        for line in re.findall(r".*done at step.*", text())[:3]:
            print(f"   {line}")
        return 0 if ok else 1
    finally:
        controller.stop()
        kubelet.stop()


if __name__ == "__main__":
    raise SystemExit(main())
