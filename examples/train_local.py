"""Local (single-process) word2vec training with per-pass checkpoints.

Port of the reference's local example (reference example/train_local.py:
1-109: same model as train_ft, local SGD, parameters saved to a tar each
pass).  Here the per-pass tar becomes an Orbax checkpoint
(ElasticCheckpointer), which is also what survives a mesh resize in the
elastic path.

    python examples/train_local.py [checkpoint_dir]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path + platform pin)

import sys
import tempfile

import jax
import numpy as np
import optax

from edl_tpu.models import word2vec
from edl_tpu.runtime.checkpoint import ElasticCheckpointer

VOCAB, CONTEXT, EMBED, BATCH, PASSES = 2048, 4, 32, 32, 2


def main() -> None:
    ckpt_dir = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="edl-tpu-w2v-")
    rng = np.random.default_rng(0)
    ctx = rng.integers(0, VOCAB, (4096, CONTEXT), dtype=np.int32)
    tgt = (ctx.sum(axis=1) % VOCAB).astype(np.int32)

    params = word2vec.init(jax.random.key(0), VOCAB, CONTEXT, EMBED)
    optimizer = optax.adam(3e-3)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(word2vec.loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    ckpt = ElasticCheckpointer(ckpt_dir)
    global_step, first = 0, None
    for p in range(PASSES):
        for lo in range(0, len(ctx) - BATCH + 1, BATCH):
            batch = (ctx[lo:lo + BATCH], tgt[lo:lo + BATCH])
            params, opt_state, loss = step(params, opt_state, batch)
            first = float(loss) if first is None else first
            global_step += 1
        # per-pass save (role of save_parameter_to_tar, train_local.py:95-96)
        ckpt.save(global_step, {"params": params, "opt_state": opt_state})
        print(f"pass {p}: step {global_step} loss {float(loss):.4f} "
              f"-> checkpoint {ckpt_dir}")
    ckpt.close()
    print(f"loss {first:.4f} -> {float(loss):.4f}")
    assert float(loss) < first


if __name__ == "__main__":
    main()
