"""Fault-tolerant elastic trainer example — word2vec (CBOW) on REAL text.

TPU-native port of the reference's flagship example
(reference example/train_ft.py:15-118: word2vec/imikolov on paddle.v2,
pserver discovery via etcd, data via the master task queue).  Here:

  * the corpus is a real text file (``examples/data/tiny_corpus.txt``,
    baked into the job image like the reference's pre-converted imikolov
    RecordIO chunks, example/Dockerfile:1-8) — tokenized and sharded to
    disk ONCE by a claim-elected seeder (``runtime.corpus`` +
    ``FileShardStore``), then leased as file shards;
  * parameters live replicated/sharded on the local device mesh
    (ElasticTrainer), not in pservers;
  * data shards are leased from the coordination service's task queue
    (TaskLeaseBatches = role of cloud_reader, train_ft.py:112) — a dead
    trainer's shard is re-dispatched after the 16 s timeout;
  * trainer count appears nowhere (the property that makes the job
    elastic, SURVEY §3.4).

Run standalone (in-process coordinator, the shipped corpus):

    python examples/train_ft.py

or as a pod entrypoint under the launcher, which exports
EDL_COORD_HOST/EDL_COORD_PORT/EDL_WORKER_NAME:

    python -m edl_tpu.runtime.launcher start_trainer

Env: ``EDL_DATA_FILE`` picks a different corpus (empty string →
synthetic fallback); ``EDL_DATA_DIR`` is where shards are written
(shared storage in a real deployment)."""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path + platform pin)

import os

import jax
import numpy as np
import optax

from edl_tpu.models import word2vec
from edl_tpu.runtime.data import ShardRegistry, TaskLeaseBatches
from edl_tpu.runtime.elastic import ElasticTrainer

VOCAB = 2048       # role of imikolov's word dict (train_ft.py:32-34)
CONTEXT = 4        # N-gram context, reference wordemb (train_ft.py:57-76)
EMBED = 32
BATCH = 32         # reference batch size (train_ft.py:113)
PASSES = int(os.environ.get("EDL_PASSES", "2"))
SHARDS = 16


def synthetic_corpus(n_examples: int = 8192, seed: int = 0):
    """Synthetic skip-gram pairs standing in for the imikolov RecordIO
    shards the reference pre-converts into its example image
    (example/Dockerfile:1-8)."""
    rng = np.random.default_rng(seed)
    ctx = rng.integers(0, VOCAB, (n_examples, CONTEXT), dtype=np.int32)
    # target fully determined by the context, so the loss falls fast
    tgt = ctx[:, 0].copy()
    return ctx, tgt


def connect_coordinator():
    """Coordinator from the launcher env, else an in-process service."""
    host = os.environ.get("EDL_COORD_HOST")
    if host:
        from edl_tpu.coord.client import CoordClient

        return CoordClient(host, int(os.environ["EDL_COORD_PORT"]))
    from edl_tpu.coord.service import PyCoordService

    return PyCoordService(passes=PASSES)


DEFAULT_CORPUS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "data", "tiny_corpus.txt")


def main() -> None:
    import tempfile

    from edl_tpu.runtime.data import FileShardStore, ensure_seeded

    worker = os.environ.get("EDL_WORKER_NAME", "local-0")
    coord = connect_coordinator()

    data_file = os.environ.get("EDL_DATA_FILE", DEFAULT_CORPUS)
    if data_file and os.path.exists(data_file):
        # REAL data: tokenize + shard the corpus to disk once (the
        # claim-elected seeder with crash takeover — ensure_seeded), then
        # everyone leases the FILES (role of RecordIO chunks + master
        # task list, reference example/train_ft.py:112)
        from edl_tpu.runtime import corpus

        data_dir = os.environ.get(
            "EDL_DATA_DIR",
            os.path.join(tempfile.gettempdir(),
                         f"edl-train-ft-{os.path.basename(data_file)}"))

        def seed(beat):
            FileShardStore.enqueue(coord, corpus.prepare_shards(
                data_file, data_dir, num_shards=SHARDS,
                vocab_size=VOCAB, context=CONTEXT, on_shard=beat))

        ensure_seeded(coord, worker, seed)
        meta = corpus.load_vocab_meta(data_dir)
        vocab_size, fetch = meta["vocab_size"], FileShardStore.fetch
        print(f"[{worker}] corpus {os.path.basename(data_file)}: "
              f"{meta['tokens']} tokens, vocab {vocab_size}, "
              f"{SHARDS} file shards in {data_dir}")
    else:
        # synthetic fallback: every worker registers the same
        # deterministic split; one CAS-elected worker enqueues
        registry = ShardRegistry()
        shard_ids = registry.register_arrays(synthetic_corpus(), SHARDS)
        if coord.kv_cas("data-seeder", b"", worker.encode()):
            registry.enqueue(coord, shard_ids)
        vocab_size, fetch = VOCAB, registry.fetch

    params = word2vec.init(jax.random.key(0), vocab_size, CONTEXT, EMBED)
    trainer = ElasticTrainer(
        word2vec.loss_fn, params, optax.adam(3e-3),
    )

    losses = []
    batches = TaskLeaseBatches(coord, worker, fetch, BATCH)
    for i, batch in enumerate(batches):
        losses.append(trainer.step(batch))
        if i % 50 == 0:
            print(f"[{worker}] step {trainer.state.step} "
                  f"pass {coord.current_pass()} loss {losses[-1]:.4f}")
    stats = coord.stats()
    print(f"[{worker}] done: {trainer.state.step} steps, "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}; "
          f"queue done={stats.done} todo={stats.todo} "
          f"dropped={stats.dropped}")
    assert losses[-1] < losses[0], "loss should decrease"


if __name__ == "__main__":
    main()
