"""Distributed MNIST-class example with prepare/train/infer subcommands.

Port of the reference's Fluid example (reference
example/fluid/recognize_digits.py:176-189 — ``prepare`` shards the dataset
to pickles, ``train`` runs the transpiled distributed loop, ``infer``
loads the saved model; static shard assignment
``idx % trainers == trainer_id``, example/fluid/common.py:24-40).

TPU-native shape: the DistributeTranspiler's pserver/trainer program split
is gone — the "distributed" part is a jit-sharded data-parallel step, and
the static shard rule survives as the non-elastic data path
(``EDL_TRAINER_ID``/``EDL_TRAINERS`` env, exported by the launcher's
static path).

    python examples/mnist.py prepare [data_dir]
    python examples/mnist.py train   [data_dir]
    python examples/mnist.py infer   [data_dir]
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path + platform pin)

import os
import pickle
import sys
import tempfile
from pathlib import Path

import jax
import numpy as np
import optax

from edl_tpu.models import mlp
from edl_tpu.runtime.checkpoint import ElasticCheckpointer

SIZES = [784, 256, 128, 10]
BATCH, EPOCHS, SHARDS = 64, 6, 8


def _default_dir() -> str:
    return os.environ.get("EDL_DATA_DIR",
                          str(Path(tempfile.gettempdir()) / "edl-tpu-mnist"))


def synthetic_mnist(n=4096, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, 784)).astype(np.float32)
    # one fixed labeling matrix across all seeds, so train and holdout
    # share the target function
    w = np.random.default_rng(42).normal(0, 1, (784, 10)).astype(np.float32)
    y = (x @ w).argmax(axis=1).astype(np.int32)  # linearly separable labels
    return x, y


def prepare(data_dir: str) -> None:
    """Shard the dataset to pickle files (role of prepare_dataset,
    reference example/fluid/common.py:6-22)."""
    out = Path(data_dir)
    out.mkdir(parents=True, exist_ok=True)
    x, y = synthetic_mnist()
    for i, idx in enumerate(np.array_split(np.arange(len(x)), SHARDS)):
        with open(out / f"shard-{i:03d}.pkl", "wb") as f:
            pickle.dump((x[idx], y[idx]), f)
    print(f"wrote {SHARDS} shards to {out}")


def cluster_reader(data_dir: str, trainer_id: int, trainers: int):
    """Static shard assignment idx % trainers == trainer_id
    (reference example/fluid/common.py:24-40)."""
    shards = sorted(Path(data_dir).glob("shard-*.pkl"))
    for i, path in enumerate(shards):
        if i % trainers != trainer_id:
            continue
        with open(path, "rb") as f:
            yield pickle.load(f)


def train(data_dir: str) -> None:
    trainer_id = int(os.environ.get("EDL_TRAINER_ID", "0"))
    trainers = int(os.environ.get("EDL_TRAINERS", "1"))
    params = mlp.init(jax.random.key(0), SIZES)
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(mlp.loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    n_steps, loss = 0, None
    for _ in range(EPOCHS):
        for x, y in cluster_reader(data_dir, trainer_id, trainers):
            for lo in range(0, len(x) - BATCH + 1, BATCH):
                params, opt_state, loss = step(
                    params, opt_state, (x[lo:lo + BATCH], y[lo:lo + BATCH]))
                n_steps += 1
    ckpt = ElasticCheckpointer(str(Path(data_dir) / "model"))
    ckpt.save(n_steps, {"params": params})
    ckpt.close()
    x, y = synthetic_mnist(512, seed=1)
    acc = float(mlp.accuracy(params, (x, y)))
    print(f"trainer {trainer_id}/{trainers}: {n_steps} steps, "
          f"loss {float(loss):.4f}, holdout acc {acc:.3f}")


def infer(data_dir: str) -> None:
    """Load the saved model and classify a batch (role of the ``infer``
    subcommand, reference example/fluid/recognize_digits.py:150-174)."""
    params = mlp.init(jax.random.key(0), SIZES)  # shape template
    ckpt = ElasticCheckpointer(str(Path(data_dir) / "model"))
    state = ckpt.restore({"params": params})
    ckpt.close()
    x, y = synthetic_mnist(64, seed=2)
    pred = np.asarray(mlp.apply(state["params"], x).argmax(axis=1))
    print(f"inferred {len(pred)} samples, acc "
          f"{float((pred == y).mean()):.3f}")


def main() -> None:
    cmd = sys.argv[1] if len(sys.argv) > 1 else "train"
    data_dir = sys.argv[2] if len(sys.argv) > 2 else _default_dir()
    if cmd == "prepare":
        prepare(data_dir)
    elif cmd == "train":
        if not list(Path(data_dir).glob("shard-*.pkl")):
            prepare(data_dir)
        train(data_dir)
    elif cmd == "infer":
        infer(data_dir)
    else:
        raise SystemExit(f"unknown subcommand {cmd!r} "
                         "(want prepare|train|infer)")


if __name__ == "__main__":
    main()
