"""Shared bootstrap for the example scripts (run as ``python examples/x.py``).

Puts the repo root on sys.path (the scripts live one level below it), and —
for images whose sitecustomize pins jax onto an accelerator platform — honors
an explicit ``JAX_PLATFORMS=cpu`` request by re-pinning via the config API,
which wins as long as the backend hasn't initialized yet.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Only when cpu is the FIRST entry: "tpu,cpu" means cpu-as-fallback and
# must still pick the accelerator (ADVICE r1).
if os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() == "cpu":
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except ImportError:
        pass
