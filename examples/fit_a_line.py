"""Linear-regression example (fit a line).

Port of the reference's simplest Fluid example (reference
example/fluid/fit_a_line.py:76-93: linear regression on the UCI housing
features, role-split via the DistributeTranspiler).  TPU-native shape: a
jitted least-squares step; distribution, when run under the launcher's
static path, is the same EDL_TRAINER_ID shard rule as mnist.py.

    python examples/fit_a_line.py
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path + platform pin)

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

FEATURES = 13  # UCI housing dimensionality (fit_a_line.py:20)
BATCH, STEPS = 32, 400


def synthetic_housing(n=2048, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, FEATURES)).astype(np.float32)
    w_true = rng.normal(0, 1, (FEATURES, 1)).astype(np.float32)
    y = x @ w_true + 0.1 * rng.normal(0, 1, (n, 1)).astype(np.float32)
    return x, y


def main() -> None:
    trainer_id = int(os.environ.get("EDL_TRAINER_ID", "0"))
    trainers = int(os.environ.get("EDL_TRAINERS", "1"))
    x, y = synthetic_housing()
    x, y = x[trainer_id::trainers], y[trainer_id::trainers]

    params = {"w": jnp.zeros((FEATURES, 1)), "b": jnp.zeros(())}
    optimizer = optax.sgd(1e-2)
    opt_state = optimizer.init(params)

    def loss_fn(params, batch):
        xb, yb = batch
        pred = xb @ params["w"] + params["b"]
        return jnp.mean((pred - yb) ** 2)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for i in range(STEPS):
        lo = (i * BATCH) % (len(x) - BATCH)
        params, opt_state, loss = step(
            params, opt_state, (x[lo:lo + BATCH], y[lo:lo + BATCH]))
        first = float(loss) if first is None else first
    print(f"trainer {trainer_id}/{trainers}: "
          f"mse {first:.4f} -> {float(loss):.4f}")
    assert float(loss) < first


if __name__ == "__main__":
    main()
