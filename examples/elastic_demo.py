"""The elastic-scheduling demo — the reference's BOSS-tutorial trace,
reproduced end-to-end in-process.

The reference's headline demo (reference doc/boss_tutorial.md:246-301):
an idle cluster sits at 18.4 % utilization; one elastic job scales to its
max (54.4 %); a second packs in (86.4 %); a third is admitted by the
autoscaler *scaling the others down* (10→3, 8→4), landing at 88.4 % with
zero pending jobs.  This script replays that scenario on the in-memory
cluster with TPU chips as the contended resource and prints the same
collector trace (SUBMITTED/PENDING/RUNNING-TRAINERS/UTILS).

    python examples/elastic_demo.py
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo-root sys.path + platform pin)

from edl_tpu.api.types import (
    RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_TPU,
    ResourceRequirements, TrainerSpec, TrainingJob, TrainingJobSpec,
)
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.observability.collector import Collector
from edl_tpu.scheduler.autoscaler import Autoscaler


def make_job(name: str, lo: int, hi: int) -> TrainingJob:
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=True,  # elastic requires FT (jobparser.go:66-68)
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "4", RESOURCE_MEMORY: "8G"},
                    limits={RESOURCE_CPU: "4", RESOURCE_MEMORY: "8G",
                            RESOURCE_TPU: "1"},
                ),
            ),
        ),
    )


def settle(scaler: Autoscaler, collector: Collector, label: str,
           ticks: int = 12) -> None:
    for _ in range(ticks):
        scaler.tick()
    print(f"--- {label}")
    collector.run_once()


def main() -> None:
    cluster = FakeCluster()
    # A 16-chip pod (2 hosts x 8 chips) — the contended resource, standing
    # in for the tutorial's 25-CPU demo cluster.
    for i in range(2):
        cluster.add_node(f"host{i}", cpu_milli=96_000, memory_mega=512_000,
                         tpu_chips=8, ici_domain="pod0")
    # Background system load (role of the k8s system pods at 18.4 %).
    cluster.add_system_pod("kube-system", "host0", cpu_request_milli=4000,
                           memory_request_mega=8000)

    scaler = Autoscaler(cluster, max_load_desired=1.0)
    collector = Collector(cluster)
    collector.run_once()  # idle snapshot

    # Wave 1: one elastic job -> scales to its max (10 trainers).
    job1 = make_job("example", 2, 10)
    cluster.create_resources(job1)
    scaler.on_add(job1)
    settle(scaler, collector, "job `example` submitted (2..10)")

    # Wave 2: second job packs into the remaining chips.
    job2 = make_job("example1", 2, 8)
    cluster.create_resources(job2)
    scaler.on_add(job2)
    settle(scaler, collector, "job `example1` submitted (2..8)")

    # Wave 3: a third job fits only if the others scale DOWN — the
    # rebalance that is the point of the reference demo.
    job3 = make_job("example2", 2, 6)
    cluster.create_resources(job3)
    scaler.on_add(job3)
    settle(scaler, collector, "job `example2` submitted (2..6) -> rebalance")

    final = {j.name: cluster.get_trainer_parallelism(j)
             for j in (job1, job2, job3)}
    pending = sum(1 for j in (job1, job2, job3)
                  if cluster.job_pods(j).running == 0)
    util = cluster.inquiry_resource()
    print(f"\nfinal trainer counts: {final}")
    print(f"pending jobs: {pending}  (reference lands at 0, "
          f"boss_tutorial.md:300-301)")
    print(f"chip utilization: {100.0 * util.tpu_limit / util.tpu_total:.1f}% "
          f"(reference peak: 88.4% CPU)")
    assert pending == 0, "all jobs should be admitted after rebalance"


if __name__ == "__main__":
    main()
